"""N-gram / prompt-lookup draft proposal + host-side deterministic accept.

Speculative decoding without a draft model (the `--spec-ngram` path):
propose the next K tokens by looking the current suffix up in the
sequence's OWN token history (prompt + generated). Summarization,
code-edit, and RAG workloads repeat long spans of their prompt, so a
suffix match is a strong predictor there — and on mismatch-heavy text
the verify pass simply rejects, costing only the verify row's extra
flat tokens (scheduler-charged, see docs/spec_decode.md).

This module is imported on the engine step path of MOCKER processes, so
it must stay jax-free (plain lists + ints; `accept_deterministic` takes
anything indexable). The distribution-preserving math lives in
`spec_decode.accept_and_finalize`; `accept_deterministic` below is its
exact specialization to one-hot draft distributions, proven equivalent
by tests/test_spec_decode.py.
"""

from __future__ import annotations

from typing import List, Sequence

# bound the history scanned per proposal so drafting stays O(window) per
# sequence per iteration on the step thread, not O(context)
NGRAM_SCAN_WINDOW = 4096


def propose(
    tokens: Sequence[int],
    k: int,
    *,
    min_match: int = 1,
    max_match: int = 4,
    window: int = NGRAM_SCAN_WINDOW,
) -> List[int]:
    """Prompt-lookup draft: find the longest suffix of `tokens` (between
    min_match and max_match tokens) that also occurs earlier in the
    history, and propose the k tokens that FOLLOWED its most recent
    earlier occurrence. Returns [] when nothing matches (the sequence
    then decodes plainly this iteration — speculation is per-seq,
    per-step opportunistic)."""
    n = len(tokens)
    if k <= 0 or n < min_match + 1:
        return []
    lo = max(0, n - window)
    hist = list(tokens[lo:n])
    h = len(hist)
    for m in range(min(max_match, h - 1), min_match - 1, -1):
        pattern = hist[h - m:]
        # scan right-to-left so the most recent occurrence wins (locality:
        # recent repetitions predict better than distant ones)
        for s in range(h - m - 1, -1, -1):
            if hist[s:s + m] == pattern:
                cont = hist[s + m : s + m + k]
                if cont:
                    return [int(t) for t in cont]
        # no occurrence of the longest suffix — try a shorter one
    return []


def propose_tree(
    tokens: Sequence[int],
    k: int,
    branches: int,
    *,
    min_match: int = 1,
    max_match: int = 4,
    window: int = NGRAM_SCAN_WINDOW,
) -> List[List[int]]:
    """Tree draft proposal: up to `branches` DISTINCT candidate
    continuations of the current suffix, from different earlier
    occurrences (most recent first, longest suffix first — branch 0 is
    exactly `propose()`'s draft, which pins tree speculation at
    branches=1 to the linear-K behavior). Later branches are clipped to
    branch 0's length so every branch's verify row fits the page
    capacity the scheduler guaranteed for the primary draft. Returns []
    when nothing matches; duplicates are dropped (verifying the same
    continuation twice buys nothing)."""
    n = len(tokens)
    if k <= 0 or branches <= 0 or n < min_match + 1:
        return []
    lo = max(0, n - window)
    hist = list(tokens[lo:n])
    h = len(hist)
    out: List[List[int]] = []
    seen = set()
    for m in range(min(max_match, h - 1), min_match - 1, -1):
        pattern = hist[h - m:]
        for s in range(h - m - 1, -1, -1):
            if hist[s:s + m] == pattern:
                cont = hist[s + m : s + m + k]
                if not cont:
                    continue
                if out:
                    cont = cont[: len(out[0])]  # clip to the primary draft
                key = tuple(cont)
                if key in seen:
                    continue
                seen.add(key)
                out.append([int(t) for t in cont])
                if len(out) >= branches:
                    return out
    return out


def accept_tree(
    drafts: Sequence[Sequence[int]], rows: Sequence[Sequence[int]]
) -> tuple:
    """Accept/reject a TREE of deterministic drafts against per-branch
    target samples; returns (emitted, winner) where `winner` indexes the
    branch whose verify row supplied the emitted suffix (the engine
    adopts that branch's forked page table; -1 = no branches were given
    or nothing beyond the correction came from a fork — adopt nothing).

    `rows[b][j]` must be a target sample at verify position j of branch
    b (position 0 fed the sequence's last real token for EVERY branch,
    so all rows sample the same position-0 distribution with the same
    per-sequence randomness — identical branch prefixes yield identical
    samples, which is what makes the trie walk well-defined).

    The walk emits one target sample per depth from the lowest-indexed
    LIVE branch (a branch stays live while its drafted tokens match the
    emitted stream), stopping after the first mismatch (that sample is
    the correction token) or after the bonus token on a full match —
    `accept_deterministic` applied down a trie instead of a chain, and
    exactly equal to it when len(drafts) == 1. Every emitted token is a
    target sample at its position, so the output distribution is the
    target's at any temperature (same argument as the linear proof in
    `accept_deterministic`'s docstring)."""
    if not drafts:
        return [], -1
    live = list(range(len(drafts)))
    out: List[int] = []
    winner = 0
    for j in range(len(drafts[0])):
        b = live[0]  # lowest-index live branch supplies the sample
        winner = b
        tok = int(rows[b][j])
        out.append(tok)
        live = [
            i for i in live
            if j < len(drafts[i]) and int(drafts[i][j]) == tok
        ]
        if not live:
            return out, winner  # mismatch everywhere: tok is the correction
    b = live[0]
    out.append(int(rows[b][len(drafts[b])]))  # bonus token
    return out, b


def accept_deterministic(
    draft: Sequence[int], sampled: Sequence[int]
) -> List[int]:
    """Accept/reject a deterministic (one-hot q) draft against target
    samples, emitting 1..len(draft)+1 tokens.

    `sampled[j]` must be a token drawn from the TARGET distribution at
    verify position j (position j fed draft[j-1], position 0 fed the
    sequence's last real token), with independent randomness per
    position. This is `spec_decode.accept_and_finalize` specialized to
    q = one-hot(draft):

    - accept prob of draft[j] is p(draft[j])/q(draft[j]) = p(draft[j]),
      which is exactly P[sampled[j] == draft[j]];
    - the rejection residual norm(max(p - q, 0)) is p restricted to
      x != draft[j] renormalized, which is exactly the law of
      sampled[j] conditioned on the mismatch;
    - all-accepted appends the bonus token sampled[K] (the position the
      verify row computed for free).

    So: emit target samples up to and including the first mismatch; on a
    full match, emit all K+1. Temperature-0 output is byte-identical to
    non-speculative decode (sampled[j] is then argmax, and the emitted
    stream is the greedy stream by induction).
    """
    out: List[int] = []
    for j, d in enumerate(draft):
        tok = int(sampled[j])
        out.append(tok)
        if tok != int(d):
            return out  # first mismatch: the target sample corrects it
    out.append(int(sampled[len(draft)]))  # bonus token
    return out
