"""N-gram / prompt-lookup draft proposal + host-side deterministic accept.

Speculative decoding without a draft model (the `--spec-ngram` path):
propose the next K tokens by looking the current suffix up in the
sequence's OWN token history (prompt + generated). Summarization,
code-edit, and RAG workloads repeat long spans of their prompt, so a
suffix match is a strong predictor there — and on mismatch-heavy text
the verify pass simply rejects, costing only the verify row's extra
flat tokens (scheduler-charged, see docs/spec_decode.md).

This module is imported on the engine step path of MOCKER processes, so
it must stay jax-free (plain lists + ints; `accept_deterministic` takes
anything indexable). The distribution-preserving math lives in
`spec_decode.accept_and_finalize`; `accept_deterministic` below is its
exact specialization to one-hot draft distributions, proven equivalent
by tests/test_spec_decode.py.
"""

from __future__ import annotations

from typing import List, Sequence

# bound the history scanned per proposal so drafting stays O(window) per
# sequence per iteration on the step thread, not O(context)
NGRAM_SCAN_WINDOW = 4096


def propose(
    tokens: Sequence[int],
    k: int,
    *,
    min_match: int = 1,
    max_match: int = 4,
    window: int = NGRAM_SCAN_WINDOW,
) -> List[int]:
    """Prompt-lookup draft: find the longest suffix of `tokens` (between
    min_match and max_match tokens) that also occurs earlier in the
    history, and propose the k tokens that FOLLOWED its most recent
    earlier occurrence. Returns [] when nothing matches (the sequence
    then decodes plainly this iteration — speculation is per-seq,
    per-step opportunistic)."""
    n = len(tokens)
    if k <= 0 or n < min_match + 1:
        return []
    lo = max(0, n - window)
    hist = list(tokens[lo:n])
    h = len(hist)
    for m in range(min(max_match, h - 1), min_match - 1, -1):
        pattern = hist[h - m:]
        # scan right-to-left so the most recent occurrence wins (locality:
        # recent repetitions predict better than distant ones)
        for s in range(h - m - 1, -1, -1):
            if hist[s:s + m] == pattern:
                cont = hist[s + m : s + m + k]
                if cont:
                    return [int(t) for t in cont]
        # no occurrence of the longest suffix — try a shorter one
    return []


def accept_deterministic(
    draft: Sequence[int], sampled: Sequence[int]
) -> List[int]:
    """Accept/reject a deterministic (one-hot q) draft against target
    samples, emitting 1..len(draft)+1 tokens.

    `sampled[j]` must be a token drawn from the TARGET distribution at
    verify position j (position j fed draft[j-1], position 0 fed the
    sequence's last real token), with independent randomness per
    position. This is `spec_decode.accept_and_finalize` specialized to
    q = one-hot(draft):

    - accept prob of draft[j] is p(draft[j])/q(draft[j]) = p(draft[j]),
      which is exactly P[sampled[j] == draft[j]];
    - the rejection residual norm(max(p - q, 0)) is p restricted to
      x != draft[j] renormalized, which is exactly the law of
      sampled[j] conditioned on the mismatch;
    - all-accepted appends the bonus token sampled[K] (the position the
      verify row computed for free).

    So: emit target samples up to and including the first mismatch; on a
    full match, emit all K+1. Temperature-0 output is byte-identical to
    non-speculative decode (sampled[j] is then argmax, and the emitted
    stream is the greedy stream by induction).
    """
    out: List[int] = []
    for j, d in enumerate(draft):
        tok = int(sampled[j])
        out.append(tok)
        if tok != int(d):
            return out  # first mismatch: the target sample corrects it
    out.append(int(sampled[len(draft)]))  # bonus token
    return out
