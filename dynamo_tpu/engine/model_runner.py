"""ModelRunner: compiled, sharded prefill/decode step functions.

XLA-first execution model (SURVEY.md §7 "continuous batching under XLA's
static shapes"):
- every step shape is drawn from a fixed bucket set (decode batch buckets,
  prefill chunk buckets) so each shape compiles once and is cached;
- the paged KV pool is carried as two sharded jax.Arrays and **donated** on
  every step — XLA updates it in place, no reallocation;
- params are placed with the ShardingPolicy's megatron-style specs over the
  (data, model, expert, seq) mesh; XLA inserts the per-block all-reduces
  over ICI;
- sampling runs fused at the end of the decode step, so one int32 per
  sequence is the only per-token device→host transfer.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import OrderedDict
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.sampling import SamplingParams, sample
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig, ShardingPolicy, make_mesh

log = logging.getLogger("dynamo_tpu.engine.runner")


def _decode_loop(
    config: ModelConfig,
    attn_impl: str,
    mesh,  # for sharded pallas attention on TP meshes (None = single dev)
    n_steps: int,
    n_logprobs: int,  # static; -1 = no logprob outputs, >=0 = top-N report
    params,
    tokens0,  # [B] int32 current token per seq — host-packed OR a device
    # array chained from the previous dispatch's output (pipelining: the
    # caller never has to sync tokens to host between dispatches)
    packed,  # int32 [B + B*MP (+B if lora) + 1]: pos|pt|adapters|step
    hist,  # None (no penalties) or int32 [B, H] token history padded with
    # vocab_size — builds the on-device count table the penalties read
    mask,  # None or bool [B, V] guided-decoding sampling mask for step 0
    # (a constrained dispatch without mask_fn runs n_steps=1 so one mask
    # covers the loop; with mask_fn the per-step masks come from the host)
    bias,  # None or f32 [B, V] additive logit bias (OpenAI logit_bias;
    # constant per request, so it rides full fused loops unlike masks)
    k_pool,
    v_pool,
    sampling: SamplingParams,
    lora=None,  # stacked multi-LoRA tree (models/lora.py)
    mask_fn=None,  # static: host callback (t, prev_tokens) -> bool [B, V]
    # advancing guided DFA states between fused steps (ordered io_callback;
    # FALLBACK for schemas too large for the device table — see `guided`)
    guided=None,  # None or (gtrans [G, V] i32, gmask [G, V] bool,
    # gstate [B] i32, gpend scalar i32): device-resident guided DFA
    # (guided/device_table.py). Per-step state advance and mask gather
    # happen in-XLA inside the scan — zero host round trips, unlike the
    # ordered-io_callback mask_fn path this replaces for bounded schemas.
    # Unguided/dead rows sit in the shared DEAD state (all-True mask).
    # gpend != 0 advances at t=0 too (the ragged tail: tok0 was sampled
    # on device by the ragged step and never folded into gstate).
):
    """n_steps decode iterations fused in one jit: forward → sample → feed
    the sampled token back, entirely on device (lax.scan). Amortizes the
    per-dispatch host sync (dominant through remote-TPU links) over n_steps
    tokens. All per-dispatch dynamic ints arrive in ONE packed array —
    each separate host array would be its own host→device transfer, and on
    a relay-attached TPU each transfer costs a full round trip (measured
    ~5-10 ms each, dwarfing the step itself). `hist` (penalties) is the
    one exception: it is batch×history sized, so it rides as its own array
    only when a request actually uses penalties.
    Returns (tokens [B, n_steps], last [B], lp, k_pool, v_pool) where lp is
    None or (tok_lp [B, T], top_ids [B, T, K], top_lps [B, T, K])."""
    B = sampling.temperature.shape[0]
    n_fields = 2 if lora is not None else 1
    MP = (packed.shape[0] - 1 - n_fields * B) // B
    positions0 = packed[:B]
    page_table = packed[B : B + B * MP].reshape(B, MP)
    adapter_idx = packed[B + B * MP : 2 * B + B * MP] if lora is not None else None
    step0 = packed[-1]

    use_pen = hist is not None
    counts0 = out0 = None
    if use_pen:
        # hist = (tokens [B, H] padded with vocab_size, prompt_len [B]);
        # the count of GENERATED tokens only (positions >= prompt_len)
        # feeds the OpenAI frequency/presence pair, the full count feeds
        # HF repetition — see sampling.apply_penalties
        hist_tok, prompt_len = hist
        V = config.vocab_size
        rows = jnp.arange(B, dtype=jnp.int32)[:, None]
        cols = jnp.arange(hist_tok.shape[1], dtype=jnp.int32)[None, :]
        # pad tokens == V scatter out of bounds and drop
        counts0 = jnp.zeros((B, V), jnp.float32).at[
            rows, hist_tok
        ].add(1.0, mode="drop")
        out_tok = jnp.where(cols >= prompt_len[:, None], hist_tok, V)
        out0 = jnp.zeros((B, V), jnp.float32).at[
            rows, out_tok
        ].add(1.0, mode="drop")

    use_guided = guided is not None
    if use_guided:
        gtrans, gmask, gstate0, gpend = guided

    def body(carry, t):
        gs = None
        if use_guided:
            carry, gs = carry[:-1], carry[-1]
        if use_pen:
            tok, kp, vp, cnt, cnt_out = carry
        else:
            (tok, kp, vp), cnt, cnt_out = carry, None, None
        pos = jnp.where(positions0 < 0, -1, positions0 + t)
        kvl = jnp.where(positions0 < 0, 0, positions0 + t + 1)
        logits, kp, vp = llama.forward(
            config, params, tok[:, None], pos[:, None], kp, vp, page_table, kvl,
            attn_impl=attn_impl, mesh=mesh, lora=lora, adapter_idx=adapter_idx,
        )
        raw = logits[:, 0, :]
        l = raw
        if use_pen:
            from dynamo_tpu.engine.sampling import apply_penalties

            l = apply_penalties(raw, cnt, cnt_out, sampling)
        m = mask
        if use_guided:
            # device-resident guided DFA: advance each row's state by the
            # token it fed this step (t>0, or t==0 under pending), then
            # gather its mask row — all in-XLA, no host round trip. Dead/
            # unguided rows self-loop in DEAD (all-True), matching the
            # host GuidedMaskContext's alive=False semantics exactly.
            adv = (t > 0) | (gpend != 0)
            gs = jnp.where(adv, gtrans[gs, tok], gs)
            m = gmask[gs]
        if mask_fn is not None:
            # guided rows in a multi-step loop: the DFA advances host-side
            # between fused steps (tok = what step t-1 sampled), so the
            # whole constrained batch rides full decode_steps loops instead
            # of collapsing to n_steps=1
            from jax.experimental import io_callback

            m = io_callback(
                mask_fn,
                jax.ShapeDtypeStruct((B, config.vocab_size), jnp.bool_),
                t, tok, ordered=True,
            )
        s = sample(l, sampling, step0 + t, mask=m, bias=bias)
        outs = (s,)
        if n_logprobs >= 0:
            from dynamo_tpu.engine.sampling import top_logprobs

            outs = (s,) + top_logprobs(raw, s, n_logprobs)
        if use_pen:
            r = jnp.arange(B, dtype=jnp.int32)
            cnt = cnt.at[r, s].add(1.0)
            cnt_out = cnt_out.at[r, s].add(1.0)
            nxt = (s, kp, vp, cnt, cnt_out)
        else:
            nxt = (s, kp, vp)
        if use_guided:
            nxt = nxt + (gs,)
        return nxt, outs

    carry0 = (tokens0, k_pool, v_pool) + ((counts0, out0) if use_pen else ())
    if use_guided:
        carry0 = carry0 + (gstate0,)
    carry, ys = lax.scan(body, carry0, jnp.arange(n_steps, dtype=jnp.int32))
    last, k_pool, v_pool = carry[0], carry[1], carry[2]
    toks = ys[0]
    lp = None
    if n_logprobs >= 0:
        # scan stacks along T as the leading axis; report [B, T, ...]
        lp = (ys[1].T, jnp.swapaxes(ys[2], 0, 1), jnp.swapaxes(ys[3], 0, 1))
    # `last` (== toks[:, -1]) is returned as its own output so a chaining
    # caller can feed it straight into the next dispatch — slicing the
    # token matrix caller-side would be an extra eager device program,
    # which through a TPU relay costs a full program round trip
    return toks.T, last, lp, k_pool, v_pool  # [B, n_steps], [B]


def _mixed_loop(
    config: ModelConfig,
    attn_impl: str,
    mesh,
    n_steps: int,
    params,
    ptok,  # [N, S] packed prefill chunk tokens (bucket-padded; N=1 legacy)
    ppos,  # [N, S] positions (-1 padding)
    ppt,  # [N, MP] per-chunk page tables
    pkvl,  # [N] per-chunk kv lens
    plast,  # scalar (N=1) or [N]: last valid index per chunk row
    padapter,  # [N] LoRA slot per chunk's sequence (None w/o LoRA)
    tokens0,
    packed,
    k_pool,
    v_pool,
    sampling: SamplingParams,
    lora=None,
):
    """One fused engine iteration under mixed scheduling: the token-
    budgeted prefill chunk set (one ragged segment per batch row) AND
    the n_steps decode loop in a single jit — ONE host sync per
    iteration instead of 1 + n_chunks. Through a relay-attached chip
    each dispatch costs a full RTT (~3.7 ms measured, docs/PERF.md), so
    the unfused packed MixedPlan pays that once per chunk; local-PCIe
    chips still save the program launches. Every chunk belongs to a
    different sequence (disjoint pages) than the decode batch and its
    packed siblings, so ordering inside the program is free for XLA to
    choose. Returns (toks [B, n_steps], last [B], chunk_logits — [V]
    for the legacy scalar plast, else [N, V] — k_pool, v_pool)."""
    logits, k_pool, v_pool = llama.forward(
        config, params, ptok, ppos, k_pool, v_pool, ppt, pkvl, plast,
        attn_impl=attn_impl, mesh=mesh, lora=lora, adapter_idx=padapter,
    )
    toks, last, _, k_pool, v_pool = _decode_loop(
        config, attn_impl, mesh, n_steps, -1, params, tokens0, packed,
        None, None, None, k_pool, v_pool, sampling, lora,
    )
    if getattr(plast, "ndim", 0) >= 1:
        chunk_logits = logits[:, 0]  # [N, V], one row per packed chunk
    else:
        chunk_logits = logits[0, 0]  # [V], legacy single-chunk caller
    return toks, last, chunk_logits, k_pool, v_pool


def _ragged_step(
    config: ModelConfig,
    attn_impl: str,
    mesh,
    params,
    tokens,  # [1, T] flat step tokens: decode batch (one each) + chunks
    positions,  # [1, T] per-token absolute positions (-1 padding)
    tok_pt,  # [T, MP] per-token page-table rows (KV writes, jnp fallback)
    tok_kvl,  # [T] per-token context lengths
    seg_pt,  # [SEG, MP] per-segment page-table rows (kernel SMEM operand)
    seg_kvl,  # [SEG] per-segment context lengths
    meta,  # [5, NW] work units (ops.ragged_paged_attention)
    gather_idx,  # [SEG_CAP] flat index of each segment's LAST token
    k_pool,
    v_pool,
    sampling: SamplingParams,  # BASE rows padded to SEG_CAP (per-seq on
    # the verify path; row_seq gathers them out to entry rows in-XLA)
    row_seq,  # int32 [SEG_CAP] base-row index per sampled row — identity
    # on the mixed path; on the verify path it maps each expanded verify
    # entry back to its sequence's base sampling row, so the staged base
    # is CACHEABLE across iterations (per-seq params are stable while
    # the per-entry expansion used to churn a fresh host build +
    # transfer every dispatch — the re-staging tax this removes)
    row_j,  # int32 [SEG_CAP] verify position per row (0 = the row's own
    # seed; j>0 folds the per-position seed (seed*1000003+j) & 0x7FFFFFFF
    # in uint32 — bit-identical to the host expansion it replaces, since
    # PRNGKey(s) for a uint32 seed is key data [0, s])
    step,  # traced scalar int32
    mask,  # bool [SEG_CAP, V] sampling mask, ALWAYS an operand (all-True
    # when no row is guided — constant treedef keeps guided-on and
    # guided-off dispatches in the same compiled variant, dynlint J004)
    bias,  # f32 [SEG_CAP, V] additive logit bias, ALWAYS an operand
    # (all-zero when no row is biased — same constant-treedef rule; lets
    # logit_bias rows ride the verify/mixed dispatch instead of pausing
    # speculation batch-wide)
):
    """The ragged mixed step: ONE forward serves the whole decode batch
    (each sequence a q_len=1 segment) and every packed prefill chunk from
    a single flat [T] token axis. Logits come back only at the SEG_CAP
    gathered last-token rows; sampling covers all of them (decode rows
    use their real per-sequence params, the rest ride padding params and
    are discarded host-side). Every shape here is a function of the T
    bucket alone, so the mixed family compiles |T buckets| variants
    instead of the (decode x chunk x pack) triple product.

    Decode steps 1..n-1 of a fused iteration run through the UNCHANGED
    _decode_loop as a second dispatch chained on this one's sampled
    tokens — its variants are the plain decode-bucket set the engine
    already pays for, and sampling row seeds/steps line up exactly with
    the legacy fused path (sample() derives randomness per row from the
    sequence seed and the step counter only)."""
    logits, k_pool, v_pool = llama.forward(
        config, params, tokens, positions, k_pool, v_pool, tok_pt, tok_kvl,
        last_index=gather_idx, attn_impl=attn_impl, mesh=mesh,
        ragged=(seg_pt, seg_kvl, meta),
    )
    seg_logits = logits[0]  # [SEG_CAP, V]
    # in-XLA sampling expansion: gather each row's base (per-seq) params,
    # then fold the verify position into the seed for j>0 rows. Matches
    # the host-side `(seed * 1000003 + j) & 0x7FFFFFFF` fold bit-for-bit:
    # key data for PRNGKey(uint32 s) is [0, s], uint32 wraparound agrees
    # with the arbitrary-precision host value mod 2^31.
    exp = jax.tree_util.tree_map(lambda a: a[row_seq], sampling)
    base_seed = exp.key[:, 1]  # u32 [SEG_CAP]
    eff = (base_seed * jnp.uint32(1000003) + row_j.astype(jnp.uint32)) \
        & jnp.uint32(0x7FFFFFFF)
    key = jnp.where(
        row_j[:, None] > 0,
        jnp.stack([jnp.zeros_like(eff), eff], axis=-1),
        exp.key,
    )
    exp = exp._replace(key=key)
    toks = sample(seg_logits, exp, step, mask=mask, bias=bias)  # [SEG_CAP]
    return toks, seg_logits, k_pool, v_pool


# device n-gram draft ring width: history tokens kept per slot. Smaller
# than the host NGRAM_SCAN_WINDOW (4096) — the match is identical for
# sequences shorter than the window, and the ring's HBM cost is
# SLOTS * W * 4 bytes
DRAFT_RING_WINDOW = 512


def _draft_ring_step(hist, lens, upd_tok, upd_n, k: int, max_match: int = 4):
    """One fused device draft step over ALL slots: append each slot's
    newly committed tokens to its history ring (shifting left on
    overflow), then run the prompt-lookup suffix match and gather k
    continuation tokens per slot — `engine.ngram_draft.propose` compiled
    to dense [SLOTS, W] ops (longest suffix m in [1, max_match] wins,
    most recent occurrence wins, continuation clipped at the history
    end), bit-identical to the host scan whenever the history fits the
    ring. Returns (hist, lens, drafts [SLOTS, k], n_prop [SLOTS]).

    hist [SLOTS, W] i32 (-1 padded), lens [SLOTS] i32, upd_tok
    [SLOTS, D] i32 (-1 padded), upd_n [SLOTS] i32. The whole warm spec
    loop's draft side is this one dispatch: the engine stages only the
    [SLOTS, D] committed-token delta and reads back only the proposals
    (sanitizer label draft_readback)."""
    SLOTS, W = hist.shape
    D = upd_tok.shape[1]
    i32 = jnp.int32
    # -- append with left-shift on overflow --------------------------------
    over = jnp.clip(lens + upd_n - W, 0, None)  # [SLOTS]
    gidx = jnp.arange(W, dtype=i32)[None, :] + over[:, None]
    hp = jnp.concatenate([hist, jnp.full((SLOTS, D), -1, i32)], axis=1)
    hist = jnp.take_along_axis(hp, gidx, axis=1)
    lens = lens - over
    pos = lens[:, None] + jnp.arange(D, dtype=i32)[None, :]
    valid = jnp.arange(D, dtype=i32)[None, :] < upd_n[:, None]
    rows = jnp.broadcast_to(jnp.arange(SLOTS, dtype=i32)[:, None], pos.shape)
    hist = hist.at[rows, jnp.where(valid, pos, W)].set(
        jnp.where(valid, upd_tok, -1), mode="drop"
    )
    lens = lens + upd_n
    # -- suffix match ------------------------------------------------------
    hpad = jnp.concatenate(
        [hist, jnp.full((SLOTS, max_match + k), -1, i32)], axis=1
    )
    s_arr = jnp.arange(W, dtype=i32)[None, :]
    best_s = jnp.full((SLOTS,), -1, i32)
    best_m = jnp.zeros((SLOTS,), i32)
    for m in range(max_match, 0, -1):  # longest suffix wins
        match = jnp.ones((SLOTS, W), bool)
        for i in range(m):
            sfx = jnp.take_along_axis(
                hist, jnp.clip(lens - m + i, 0, W - 1)[:, None], axis=1
            )  # [SLOTS, 1]
            match = match & (hpad[:, i : i + W] == sfx)
        # candidate start s needs the full m-gram AND >= 1 continuation
        # token before the suffix itself: s + m <= len - 1
        match = match & ((s_arr + m) <= (lens[:, None] - 1))
        match = match & (lens[:, None] >= m + 1)
        cand = jnp.where(match, s_arr, -1).max(axis=1)  # most recent
        take = (best_s < 0) & (cand >= 0)
        best_s = jnp.where(take, cand, best_s)
        best_m = jnp.where(take, i32(m), best_m)
    start = best_s + best_m
    idx = start[:, None] + jnp.arange(k, dtype=i32)[None, :]
    drafts = jnp.take_along_axis(hpad, jnp.clip(idx, 0, None), axis=1)
    n_prop = jnp.where(best_s >= 0, jnp.clip(lens - start, 0, k), 0)
    return hist, lens, drafts, n_prop


class _GuidedMaskTrampoline:
    """Identity-stable host callback for `_decode_loop`'s per-step guided
    masks: the jit cache keys static args by hash, so the callback-bearing
    program must trace against ONE object per runner — the per-dispatch
    DFA context (engine GuidedMaskContext: row matchers + state copies) is
    swapped into `ctx` right before each dispatch. Safe with async
    dispatch because the engine materializes every dispatch's sampled
    tokens before it builds the next plan, so at most one context is live
    at a time (asserted)."""

    def __init__(self):
        self.ctx = None

    def __call__(self, t, prev_tokens):
        ctx = self.ctx
        assert ctx is not None, "guided mask callback fired without context"
        return np.asarray(ctx(int(t), np.asarray(prev_tokens)), dtype=bool)


class _CompiledFamily:
    """Wraps one jitted step-function family to count distinct compiled
    variants (jit cache growth) and the cumulative wall seconds of calls
    that compiled (trace+lower+compile — the host-side stall each new
    bucket costs). The ragged path's compile-cardinality collapse is
    invisible without this; compile_stats() feeds the worker /metrics
    gauges and the goodput report's extras["compile"]."""

    def __init__(self, name: str, fn):
        self.name = name
        self._fn = fn
        self.variants = 0
        self.compile_s = 0.0
        self.calls = 0

    def _cache_size(self):
        try:
            return self._fn._cache_size()
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        self.calls += 1
        before = self._cache_size()
        t0 = time.monotonic()
        out = self._fn(*args, **kwargs)
        after = self._cache_size()
        if before is not None and after is not None and after > before:
            self.variants += after - before
            self.compile_s += time.monotonic() - t0
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "variants": self.variants,
            "compile_s": round(self.compile_s, 4),
            "calls": self.calls,
        }


# Wire layout version for P→D / cross-worker KV payloads. v2 = token-major
# [L, n, PS, Hk, D]; v1 (implicit, no field) was head-major. Mirrors the
# disk tier's BLOCK_LAYOUT_VERSION: in a mixed-version cluster (rolling
# upgrade) an old-layout peer's bytes sliced under the new axis order import
# transposed KV silently — reject and force recompute instead.
KV_WIRE_LAYOUT_VERSION = 2


class KvWireLayoutMismatch(ValueError):
    pass


def kv_arrays_to_payload(k: np.ndarray, v: np.ndarray, tp: int = 1) -> Dict[str, Any]:
    """KV wire format for P→D transfer and G2 offload: [L, n, PS, Hk, D]
    (token-major, page axis 1 — the pool layout) arrays as raw bytes +
    shape/dtype metadata. Single definition — the engine and host tier
    must not re-implement it.

    Cross-TP layout handshake (ref docs/design-docs/kvbm-design.md:161–237,
    esp. :188–197 — the reference negotiates serialized layout metadata and
    permutes blocks when P and D run different TP degrees): the wire format
    is always DENSE FULL-HEAD pages — export all-gathers the head shards
    over ICI, import scatters into the local pool under whatever sharding
    the importer's mesh uses, with GSPMD inserting the reshard. So a TP=1
    prefill worker and a TP=4 decode worker interoperate without an
    explicit permute protocol; the metadata below (page geometry + exporter
    tp degree) lets the importer VALIDATE compatibility and fall back to
    local recompute instead of adopting mis-shaped bytes."""
    out_extra = {}
    if v.shape != k.shape:
        # MLA pools are asymmetric: k = latent pages, v = 1-wide stub
        out_extra["v_shape"] = list(v.shape)
    return {
        "data": True,
        "k": k.tobytes(),
        "v": v.tobytes(),
        "shape": list(k.shape),
        "dtype": str(k.dtype),
        **out_extra,
        "n_pages": int(k.shape[1]),
        "layout": KV_WIRE_LAYOUT_VERSION,
        # layout handshake metadata: [L, n, PS, Hk, D] geometry, explicit
        "page_size": int(k.shape[2]),
        "kv_heads": int(k.shape[3]),
        "head_dim": int(k.shape[4]),
        "layers": int(k.shape[0]),
        "tp": int(tp),
    }


def layer_group_bounds(num_layers: int, groups: int) -> List[Tuple[int, int]]:
    """Contiguous [lo, hi) layer slabs for the streamed onboard: `groups`
    near-equal groups, the earlier ones taking the remainder so the first
    (blocking) transfer is never the runt."""
    g = max(1, min(int(groups), int(num_layers)))
    base, rem = divmod(int(num_layers), g)
    bounds: List[Tuple[int, int]] = []
    lo = 0
    for i in range(g):
        hi = lo + base + (1 if i < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def kv_quant_arrays_to_payload(kq, ks, vq, vs) -> Dict[str, Any]:
    """Native int8+scales KV payload for LOCAL tier promotion (engine →
    runner in one process; arrays stay arrays, no byte serialization).
    Carries the tier codec's per-(token, head) q/s pair in the pool
    stacking [L, n, PS, Hk, D] / [L, n, PS, Hk] so an int8 device pool
    adopts it without a dequantize/requantize round trip. The
    CROSS-WORKER wire stays dense (kv_arrays_to_payload) — heterogeneous
    workers keep interoperating."""
    return {
        "data": True,
        "quant": "int8_ts",
        "kq": kq, "ks": ks, "vq": vq, "vs": vs,
        "shape": list(kq.shape),
        "n_pages": int(kq.shape[1]),
        "layout": KV_WIRE_LAYOUT_VERSION,
        "page_size": int(kq.shape[2]),
        "kv_heads": int(kq.shape[3]),
        "head_dim": int(kq.shape[4]),
        "layers": int(kq.shape[0]),
    }


def kv_payload_incompatible(
    payload: Dict[str, Any],
    page_shape: Tuple[int, int, int, int],
    dtype: Optional[str] = None,
) -> Optional[str]:
    """Reason string when `payload` cannot be imported into a pool whose
    per-page geometry is `page_shape` = (L, PS, Hk, D) and (optionally)
    whose wire dtype name is `dtype`; None when compatible. Wire version,
    page geometry and dtype must match exactly — the exporter's TP degree
    is deliberately NOT checked (the dense full-head wire makes it
    irrelevant; see kv_arrays_to_payload)."""
    if payload.get("layout") != KV_WIRE_LAYOUT_VERSION:
        return f"layout {payload.get('layout')} != {KV_WIRE_LAYOUT_VERSION}"
    L, PS, Hk, D = page_shape
    shape = payload.get("shape") or []
    if len(shape) != 5:
        return f"malformed shape {shape}"
    got = (shape[0], shape[2], shape[3], shape[4])
    if got != (L, PS, Hk, D):
        return f"page geometry {got} != local (L={L}, PS={PS}, Hk={Hk}, D={D})"
    if dtype is not None and payload.get("dtype") != dtype:
        return f"dtype {payload.get('dtype')} != local {dtype}"
    return None


def kv_payload_to_arrays(payload: Dict[str, Any], page_shape=None, dtype=None):
    """Inverse of kv_arrays_to_payload; None if the payload carries no data
    (simulated workers). Raises KvWireLayoutMismatch when the sender used a
    different pool layout version or (when `page_shape`/`dtype` is given) a
    different page geometry or element type — the importer must fail the
    transfer (recompute locally) rather than adopt mis-shaped bytes."""
    if not payload or not payload.get("k"):
        return None
    if payload.get("layout") != KV_WIRE_LAYOUT_VERSION:
        raise KvWireLayoutMismatch(
            f"kv wire layout {payload.get('layout')} != {KV_WIRE_LAYOUT_VERSION}"
        )
    if page_shape is not None:
        bad = kv_payload_incompatible(payload, page_shape, dtype)
        if bad:
            raise KvWireLayoutMismatch(bad)
    import ml_dtypes

    name = payload["dtype"]
    dtype = np.dtype(ml_dtypes.bfloat16) if "bfloat16" in name else np.dtype(name)
    shape = tuple(payload["shape"])
    v_shape = tuple(payload.get("v_shape") or shape)
    k = np.frombuffer(payload["k"], dtype=dtype).reshape(shape)
    v = np.frombuffer(payload["v"], dtype=dtype).reshape(v_shape)
    return k, v


class BucketOverflowError(ValueError):
    """A dispatch needs a shape past the largest configured bucket. Carries
    what overflowed so the engine can degrade gracefully — shed chunks
    from the pack and defer them to the next iteration — instead of
    failing every sequence in the plan mid-iteration."""

    def __init__(self, n: int, buckets: Sequence[int]):
        super().__init__(f"{n} exceeds largest bucket {buckets[-1]}")
        self.n = n
        self.largest = buckets[-1]


def _next_bucket(buckets: Sequence[int], n: int) -> int:
    for b in buckets:
        if b >= n:
            return b
    raise BucketOverflowError(n, buckets)


class ModelRunner:
    supports_logit_bias = True  # engine gates biased requests on this

    def __init__(
        self,
        config: ModelConfig,
        mesh_config: Optional[MeshConfig] = None,
        *,
        num_pages: int = 512,
        page_size: int = 16,
        max_pages_per_seq: int = 128,
        decode_buckets: Sequence[int] = (1, 2, 4, 8, 16, 32, 64),
        prefill_buckets: Sequence[int] = (16, 32, 64, 128, 256, 512, 1024),
        ragged_buckets: Sequence[int] = (32, 64, 128, 256, 512, 1024, 2048),
        dtype=jnp.bfloat16,
        seed: int = 0,
        params: Optional[Any] = None,
        devices: Optional[list] = None,
        attn_impl: Optional[str] = None,  # None → pallas on TPU, jnp elsewhere
        draft_config: Optional[ModelConfig] = None,  # enables spec decode
        draft_params: Optional[Any] = None,
        spec_gamma: int = 4,  # draft tokens proposed per verify pass
        lora_slots: int = 0,  # >0 enables multi-LoRA (slot 0 = base)
        lora_rank: int = 8,
        lora_targets=None,  # defaults to models/lora.py DEFAULT_TARGETS
        quantize: Optional[str] = None,  # "int8" → weight-only quant
        kv_quantize: Optional[str] = None,  # "int8" → quantized KV pools
    ):
        self.config = config
        self._sanitizer = None  # set by attach_sanitizer (engine opt-in)
        self.mesh_config = mesh_config or MeshConfig()
        self.mesh = make_mesh(self.mesh_config, devices)
        self.policy = ShardingPolicy(self.mesh)
        # pipeline parallelism: layer-stacked params and the KV pool shard
        # their leading [L] axis over `pipe`; step functions run the GPipe
        # schedule (ops/pipeline_parallel.py). v1 composition envelope —
        # the schedule's inner ops are plain jnp, so other mesh axes and
        # the feature planes that thread extra per-layer state are gated
        # off explicitly rather than silently miscomputed.
        self.pp = self.mesh_config.pipe > 1
        if self.pp:
            from dynamo_tpu.ops import pipeline_parallel as _ppmod

            mc = self.mesh_config
            if (mc.model, mc.expert, mc.seq, mc.data) != (1, 1, 1, 1):
                raise NotImplementedError(
                    "pipe>1 composes with no other mesh axis yet "
                    f"(got {mc.shape})"
                )
            if config.n_layers % mc.pipe != 0:
                raise ValueError(
                    f"{config.n_layers} layers not divisible by "
                    f"pipe={mc.pipe} stages"
                )
            if draft_config is not None or lora_slots > 0 or kv_quantize:
                raise NotImplementedError(
                    "speculative decoding / LoRA / int8-KV are not wired "
                    "on the pipeline-parallel path yet"
                )
            _ppmod._check(config)  # dense GQA family only
            self._ppmod = _ppmod
        # mesh spanning several processes (multi-host group,
        # parallel/multihost.py): pool reads must gather to a replicated
        # sharding before device_get — remote shards aren't addressable
        self.multihost = any(
            d.process_index != jax.process_index() for d in self.mesh.devices.flat
        )
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.decode_buckets = tuple(decode_buckets)
        self.prefill_buckets = tuple(prefill_buckets)
        # packed-prefill row-count buckets: the legacy fused mixed program
        # compiles per (decode bucket, chunk bucket, pack bucket) triple
        self.pack_buckets = (1, 2, 4, 8, 16, 32)
        # ragged flat-token mixed path: ONE [T] bucket per compile. The
        # engine inserts mixed_prefill_tokens + max decode batch via
        # ensure_ragged_bucket so the scheduler's budget IS a compile
        # bucket (full mixed iterations never round up).
        self.ragged_buckets = tuple(sorted(ragged_buckets))
        self.ragged_q_block = 8
        self.dtype = dtype

        t0 = time.monotonic()
        owns_params = params is None
        if params is None:
            params = llama.init_params(config, jax.random.PRNGKey(seed), dtype)
        self.quantize = quantize
        if quantize in ("int8", "fp8"):
            from dynamo_tpu.models.quant import quantize_params

            # donate only self-initialized trees: donation frees each bf16
            # leaf as it converts (halves peak HBM during quantization) but
            # deletes the caller's arrays on accelerator backends
            params = quantize_params(params, mode=quantize, donate=owns_params)
        elif quantize is not None:
            raise ValueError(f"unknown quantize mode {quantize!r}")
        self.params = jax.device_put(params, self.policy.params_sharding(params))
        # padding writes scatter to page index == num_pages, out of bounds,
        # and are dropped (scatter mode="drop" in llama._write_kv)
        self.kv_quantize = kv_quantize
        # transfer-path page movement via the Pallas batched copy kernels
        # (ops/block_copy.py) instead of XLA gather/scatter — opt-in until
        # a hardware A/B lands (same rollout policy as attn_impl).
        # Single-device pools run the plain pallas_call; TP-only meshes run
        # it under shard_map over the head-sharded pool (per-shard page
        # streams, zero collectives — the decode_paged_attention_sharded
        # pattern). Other mesh axes keep the XLA path (GSPMD partitions it).
        import os

        mc = self.mesh_config
        tp_only_mesh = (
            mc.model > 1 and mc.data == mc.expert == mc.seq == mc.pipe == 1
        )
        flag = os.environ.get("DYN_KV_COPY_KERNEL", "").lower()
        self._kv_copy_kernel = (
            flag in ("1", "true", "on", "yes")
            and (self.mesh_config.n_devices == 1 or tp_only_mesh)
        )
        self._kv_copy_sharded = self._kv_copy_kernel and tp_only_mesh
        # non-TPU runs (CPU tests) execute the copy kernels in interpret
        # mode (platform from the mesh's devices, like attn_impl)
        self._kv_copy_interpret = (
            self.mesh.devices.flat[0].platform != "tpu"
        )
        k_pool, v_pool = llama.make_kv_pool(
            config, num_pages, page_size, dtype, kv_quantize=kv_quantize
        )
        kv_sharding = self.policy.kv_pool_sharding_tree(k_pool)
        self.k_pool = jax.device_put(k_pool, kv_sharding)
        self.v_pool = jax.device_put(v_pool, kv_sharding)
        log.info(
            "runner ready: %s params+pool placed in %.1fs (mesh %s, %d pages x %d tokens)",
            config.name, time.monotonic() - t0, self.mesh_config.shape, num_pages, page_size,
        )

        # speculative decoding: the draft model owns parallel KV pools
        # addressed by the SAME page tables (block management, prefix
        # sharing, preemption all come for free; pages onboarded from the
        # host tier lack draft KV, which only costs acceptance rate, never
        # correctness — the verify pass is authoritative)
        self.draft_config = draft_config
        self.spec_gamma = spec_gamma
        if draft_config is not None:
            if draft_params is None:
                draft_params = llama.init_params(
                    draft_config, jax.random.PRNGKey(seed + 1), dtype
                )
            self.draft_params = jax.device_put(
                draft_params, self.policy.params_sharding(draft_params)
            )
            dk, dv = llama.make_kv_pool(
                draft_config, num_pages, page_size, dtype, kv_quantize=kv_quantize
            )
            dk_sharding = self.policy.kv_pool_sharding_tree(dk)
            self.draft_k_pool = jax.device_put(dk, dk_sharding)
            self.draft_v_pool = jax.device_put(dv, dk_sharding)

        # multi-LoRA: stacked adapter factors, one slot per adapter, batched
        # per-sequence adapter indices through every step function
        self.lora = None
        self._adapter_slots: Dict[str, int] = {}
        self.lora_rank = lora_rank
        if lora_slots > 0:
            from dynamo_tpu.models import lora as lora_mod

            self.lora_targets = tuple(lora_targets or lora_mod.DEFAULT_TARGETS)
            tree = lora_mod.init_lora_params(
                config, lora_slots + 1, lora_rank, self.lora_targets, dtype
            )
            self.lora = jax.device_put(tree, self.policy.params_sharding(tree))

        if attn_impl is None:
            platform = self.mesh.devices.flat[0].platform
            # pallas on a real accelerator; TP meshes run the kernel inside
            # shard_map over the model axis (heads are independent). Other
            # parallel axes (data/expert/seq) are not yet covered by the
            # sharded wrappers, so those meshes keep the jnp path (GSPMD
            # partitions it)
            mc = self.mesh_config
            tp_only = mc.data == mc.expert == mc.seq == 1
            attn_impl = "pallas" if (platform != "cpu" and tp_only) else "jnp"
        self.attn_impl = attn_impl
        # static mesh handle threaded to forward for sharded kernels / ring
        self._fwd_mesh = self.mesh if self.mesh_config.n_devices > 1 else None

        # prefill uses the flash kernel on TPU (S>1), jnp elsewhere; with a
        # seq mesh axis, prefill goes sequence-parallel (ring attention)
        self.sp_enabled = self.mesh_config.seq > 1
        # per-family compile observability (variant counts + compile
        # seconds); see _CompiledFamily / compile_stats()
        self._families: Dict[str, _CompiledFamily] = {}

        def _family(name, fn):
            fam = _CompiledFamily(name, fn)
            self._families[name] = fam
            return fam

        self._jit_forward = _family("forward", jax.jit(
            partial(llama.forward, self.config),
            donate_argnums=(3, 4),  # k_pool, v_pool
            static_argnames=("attn_impl", "mesh", "sp_has_prior"),
        ))
        self._jit_sample = jax.jit(sample)
        self._jit_decode_loop = _family("decode_loop", jax.jit(
            partial(_decode_loop, self.config, self.attn_impl, self._fwd_mesh),
            static_argnums=(0, 1),  # n_steps, n_logprobs
            static_argnames=("mask_fn",),  # guided per-step mask callback
            donate_argnums=(8, 9),  # k_pool, v_pool
        ))
        # one trampoline per runner: static-arg identity keys the jit
        # cache, so the guided-callback program compiles once per bucket
        self._mask_tramp = _GuidedMaskTrampoline()
        # cached all-True ragged sampling masks per row-cap (the mask is a
        # permanent _ragged_step operand; unconstrained dispatches reuse
        # one device-resident array instead of re-transferring [SEG, V])
        self._true_mask_cache: Dict[int, jax.Array] = {}
        self._zero_bias_cache: Dict[int, jax.Array] = {}
        # cached identity (row_seq, row_j) maps per row cap — the mixed
        # path's no-op for the ragged step's in-XLA sampling expansion
        self._row_map_cache: Dict[int, Tuple[jax.Array, jax.Array]] = {}
        # device-resident guided DFA staging (combined transition/mask
        # tables keyed by schema uids) + state scratch; see _stage_guided
        self._guided_dev_cache: "OrderedDict[Any, Tuple[jax.Array, jax.Array]]" = (
            OrderedDict()
        )
        # the engine's guided-fusion gate: per-step masks ride the decode
        # loop's host callback / the ragged step's mask operand, neither
        # of which the PP loop carries
        self.guided_fused = not self.pp
        if self.pp:
            from dynamo_tpu.parallel.mesh import AXIS_PIPE

            self._jit_pp_prefill = jax.jit(
                partial(self._ppmod.pp_forward, self.config),
                donate_argnums=(3, 4),  # k_pool, v_pool
                static_argnames=("mesh", "axis"),
            )
            self._jit_pp_decode = jax.jit(
                partial(
                    self._ppmod.pp_decode_loop, self.config, self.mesh,
                    AXIS_PIPE,
                ),
                static_argnums=(0,),  # n_steps
                donate_argnums=(5, 6),  # k_pool, v_pool
            )
        if not self.pp:
            self._jit_mixed = _family("mixed", jax.jit(
                partial(_mixed_loop, self.config, self.attn_impl,
                        self._fwd_mesh),
                static_argnums=(0,),  # n_steps
                donate_argnums=(10, 11),  # k_pool, v_pool
            ))
            self._jit_ragged = _family("ragged", jax.jit(
                partial(_ragged_step, self.config, self.attn_impl,
                        self._fwd_mesh),
                donate_argnums=(9, 10),  # k_pool, v_pool
            ))
        # device n-gram draft ring (_draft_ring_step): registered
        # UNCONDITIONALLY so spec-on and spec-off runners expose the same
        # family set (pinned by test_spec_decode); it compiles only when
        # the engine enables device drafting (ensure_draft_ring warms it
        # before the sanitizer's recompile-tripwire freeze)
        self._jit_draft_ring = _family("draft", jax.jit(
            _draft_ring_step,
            static_argnums=(4, 5),  # k, max_match
            donate_argnums=(0, 1),  # hist, lens
        ))
        self._draft_ring = None  # (hist_dev, lens_dev) once ensured
        self._draft_ring_host = None  # (np hist, np lens) mirror
        self._draft_ring_dirty = False  # mirror edited → restage
        self._draft_ring_shape = None  # (slots, window, delta_cap)
        # ragged flat-token mixed dispatch: default ON wherever the fused
        # mixed path runs; DYN_RAGGED_MIXED=0 forces the legacy [N, S]
        # padded path (the A/B baseline), =1 forces it on. PP/SP keep the
        # legacy fallback; LoRA batches carry per-row adapters the single
        # flat row cannot, and MLA has no ragged attention yet.
        _renv = os.environ.get("DYN_RAGGED_MIXED", "").lower()
        if _renv in ("1", "true", "on", "yes"):
            _ragged_ok = True
        elif _renv in ("0", "false", "off", "no"):
            _ragged_ok = False
        else:
            _ragged_ok = True
        self.ragged_mixed = (
            _ragged_ok and not self.pp and not self.sp_enabled
            and self.lora is None and not config.is_mla
        )
        # device-resident sampling cache: batches re-send identical sampling
        # params every dispatch; transferring them each time costs one relay
        # round trip PER ARRAY (see _decode_loop)
        self._sampling_cache: Dict[Any, SamplingParams] = {}
        if draft_config is not None:
            from dynamo_tpu.engine.spec_decode import spec_rounds

            self._jit_spec = jax.jit(
                partial(
                    spec_rounds, self.config, draft_config,
                    self.attn_impl, self.attn_impl, self._fwd_mesh,
                ),
                static_argnums=(0, 1),  # gamma, n_rounds
                donate_argnums=(6, 7, 8, 9),  # both KV pool pairs
            )
            self._jit_draft_forward = jax.jit(
                partial(llama.forward, draft_config),
                donate_argnums=(3, 4),
                static_argnames=("attn_impl",),
            )

    # -- steps -------------------------------------------------------------
    def prefill(
        self,
        tokens: List[int],
        start_pos: int,
        page_table_row: List[int],
        prior_len: int,
        adapter: int = 0,
        mm: Optional[Dict[str, Any]] = None,  # {"embeds": [n,E], "offsets": [n]}
    ) -> jax.Array:
        """Run one prefill chunk for a single sequence. `tokens` are the
        uncomputed prompt tokens starting at absolute position `start_pos`;
        `prior_len` is the context length already in the pool (prefix-cache
        hits + earlier chunks). `mm` injects multimodal embeddings at
        chunk-local offsets. Returns last-token logits [V] (device)."""
        tok, pos, pt, kv_lens, n = self._prep_prefill(tokens, start_pos, page_table_row, prior_len)
        if self.pp:
            if mm is not None:
                raise NotImplementedError(
                    "multimodal prefill is not wired on the PP path yet"
                )
            logits, self.k_pool, self.v_pool = self._jit_pp_prefill(
                self.params, tok, pos, self.k_pool, self.v_pool, pt, kv_lens,
                mesh=self.mesh, axis="pipe",
            )
            return logits[0, n - 1]
        impl = "ring" if self.sp_enabled else self.attn_impl
        mm_embeds, mm_mask = self._mm_arrays(mm, tok.shape[1])
        logits, self.k_pool, self.v_pool = self._jit_forward(
            self.params, tok, pos, self.k_pool, self.v_pool, pt, kv_lens,
            jnp.int32(n - 1), attn_impl=impl,
            mesh=self.mesh if impl == "ring" else self._fwd_mesh,
            sp_has_prior=prior_len > 0,
            lora=self.lora,
            adapter_idx=jnp.asarray([adapter], jnp.int32) if self.lora is not None else None,
            mm_embeds=mm_embeds, mm_mask=mm_mask,
        )
        return logits[0, 0]

    def _mm_arrays(self, mm: Optional[Dict[str, Any]], S: int):
        """(mm_embeds [1,S,E], mm_mask [1,S]) padded to the bucket, or
        (None, None)."""
        if mm is None:
            return None, None
        E = self.config.dim
        embeds = np.zeros((1, S, E), np.float32)
        mask = np.zeros((1, S), bool)
        for row, off in zip(mm["embeds"], mm["offsets"]):
            embeds[0, off] = row
            mask[0, off] = True
        return jnp.asarray(embeds), jnp.asarray(mask)

    def _prep_prefill(self, tokens: List[int], start_pos: int, page_table_row: List[int], prior_len: int):
        """Bucket-pad one prefill chunk into device inputs (shared by the
        target and draft prefill paths)."""
        n = len(tokens)
        S = _next_bucket(self.prefill_buckets, n)
        tok = np.zeros((1, S), np.int32)
        tok[0, :n] = tokens
        pos = np.full((1, S), -1, np.int32)
        pos[0, :n] = np.arange(start_pos, start_pos + n)
        pt = self._pad_page_table([page_table_row])
        kv_lens = np.asarray([prior_len + n], np.int32)
        return jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(pt), jnp.asarray(kv_lens), n

    def decode(
        self,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        kv_lens: List[int],
        sampling,  # SamplingParams or dict of host lists
        step: int,
    ) -> np.ndarray:
        """One decode step (thin wrapper over the fused loop so single-step
        and multi-step use the identical compiled path and attn_impl).
        Returns sampled token ids [B_bucket] (host numpy)."""
        out = self.decode_multi(1, tokens, positions, page_tables, sampling, step)
        return out[:, 0]

    def attach_sanitizer(self, san) -> None:
        """Adopt the engine's runtime sanitizer: staging / readback sites
        below run inside named allow_transfer scopes so the engine can
        hold `jax.transfer_guard("disallow")` across whole dispatches."""
        self._sanitizer = san

    def layout_table(self):
        """(name, live array, declared NamedSharding) rows for every model
        param and KV pool — the statically-derived layout contract
        (ShardingPolicy over parallel/mesh.py's canonical spec tables)
        zipped with the arrays that must satisfy it. The sanitizer's
        layout guard diffs live `jax.Array.sharding` against these at
        warm-path entry; dynlint's DYN-S rules check the same tables
        statically (docs/static_analysis.md)."""
        rows = []

        def _walk(prefix, tree, shardings):
            leaves = jax.tree_util.tree_leaves_with_path(tree)
            wants = jax.tree_util.tree_leaves(shardings)
            for (path, leaf), want in zip(leaves, wants):
                name = prefix + "/".join(
                    str(getattr(k, "key", k)) for k in path
                )
                rows.append((name.rstrip("/"), leaf, want))

        _walk("params/", self.params,
              self.policy.params_sharding(self.params))
        _walk("k_pool/", self.k_pool,
              self.policy.kv_pool_sharding_tree(self.k_pool))
        _walk("v_pool/", self.v_pool,
              self.policy.kv_pool_sharding_tree(self.v_pool))
        if getattr(self, "draft_params", None) is not None:
            _walk("draft_params/", self.draft_params,
                  self.policy.params_sharding(self.draft_params))
        if getattr(self, "draft_k_pool", None) is not None:
            _walk("draft_k_pool/", self.draft_k_pool,
                  self.policy.kv_pool_sharding_tree(self.draft_k_pool))
            _walk("draft_v_pool/", self.draft_v_pool,
                  self.policy.kv_pool_sharding_tree(self.draft_v_pool))
        if getattr(self, "lora", None) is not None:
            _walk("lora/", self.lora,
                  self.policy.params_sharding(self.lora))
        return rows

    def _allow(self, label: str):
        san = self._sanitizer
        return contextlib.nullcontext() if san is None else san.allow_transfer(label)

    def _adapter_array(self, adapters: Optional[List[int]], B: int):
        if self.lora is None:
            return None
        idx = np.zeros(B, np.int32)
        if adapters:
            idx[: len(adapters)] = adapters
        return jnp.asarray(idx)

    def decode_multi(
        self,
        n_steps: int,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        sampling,  # SamplingParams or dict of host lists
        step: int,
        adapters: Optional[List[int]] = None,
        masks: Optional[np.ndarray] = None,
        biases: Optional[np.ndarray] = None,
        mask_fn=None,
        guided_dev=None,
    ) -> np.ndarray:
        """n_steps fused decode iterations (one host sync total). Page
        tables must already cover positions[i] + n_steps slots. Returns
        sampled tokens [B_bucket, n_steps]."""
        toks, _ = self.decode_multi_async(
            n_steps, tokens, positions, page_tables, sampling, step, adapters,
            masks=masks, biases=biases, mask_fn=mask_fn, guided_dev=guided_dev,
        )
        with self._allow("token_readback"):
            return np.asarray(jax.device_get(toks))

    def decode_multi_ex(
        self,
        n_steps: int,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        sampling,
        step: int,
        adapters: Optional[List[int]] = None,
        n_logprobs: int = -1,
        histories: Optional[List[List[int]]] = None,
        prompt_lens: Optional[List[int]] = None,
        masks: Optional[np.ndarray] = None,
        biases: Optional[np.ndarray] = None,
        mask_fn=None,
        guided_dev=None,
    ):
        """decode_multi with the sampling extras: `histories` (per-sequence
        prompt+generated token ids) switches on repetition/frequency/
        presence penalties — `prompt_lens[i]` marks where generated output
        starts in histories[i] (frequency/presence are output-only; absent
        = whole history is prompt); `n_logprobs` >= 0 additionally returns
        (tok_lp [B, T], top_ids [B, T, K], top_lps [B, T, K]) host arrays.
        Returns (sampled [B, T], lp | None)."""
        out = self.decode_multi_async(
            n_steps, tokens, positions, page_tables, sampling, step, adapters,
            n_logprobs=n_logprobs, histories=histories, prompt_lens=prompt_lens,
            masks=masks, biases=biases, mask_fn=mask_fn, guided_dev=guided_dev,
        )
        with self._allow("token_readback"):
            if n_logprobs >= 0:
                toks, _, lp = out
                toks_h, lp_h = jax.device_get((toks, lp))
                return np.asarray(toks_h), tuple(np.asarray(a) for a in lp_h)
            toks, _ = out
            return np.asarray(jax.device_get(toks)), None

    def decode_multi_async(
        self,
        n_steps: int,
        tokens,  # List[int] OR device int32 [>=B] (previous out[:, -1])
        positions: List[int],
        page_tables: List[List[int]],
        sampling,
        step: int,
        adapters: Optional[List[int]] = None,
        n_logprobs: int = -1,
        histories: Optional[List[List[int]]] = None,
        prompt_lens: Optional[List[int]] = None,
        masks: Optional[np.ndarray] = None,  # [n, V] bool guided masks
        biases: Optional[np.ndarray] = None,  # [n, V] f32 logit_bias rows
        mask_fn=None,  # GuidedMaskContext: per-step host-advanced masks,
        # letting constrained rows ride full n_steps fused loops (the
        # static `mask` covers step 0 semantics when mask_fn is None)
        guided_dev=None,  # (tables, row_entries, pending): device-resident
        # guided DFA plan — tables a deduped List[DeviceGuidedTable],
        # row_entries[i] None (unguided row) or (table_idx, local_state).
        # Replaces mask_fn's per-step io_callback with an in-XLA
        # advance+gather for bounded schemas (see _decode_loop `guided`);
        # mask_fn wins when both are given (the host fallback).
    ):
        """decode_multi without the host sync: returns (toks, last) DEVICE
        arrays — toks [B_bucket, n_steps] and last [B_bucket] (the final
        column, produced inside the jit). `tokens` may be the previous
        dispatch's `last`, so consecutive dispatches pipeline on device
        with no round trip between them — the caller device_gets token
        batches one dispatch behind the chip (the continuous-batching
        engine overlaps its bookkeeping the same way).
        With n_logprobs >= 0 the return grows to (toks, last, lp) — see
        decode_multi_ex."""
        n = len(positions)
        B = _next_bucket(self.decode_buckets, n)
        pt = self._pad_page_table(page_tables, B)
        MP = pt.shape[1]
        # one packed transfer for all per-dispatch ints (see _decode_loop)
        packed = np.zeros(B * (1 + MP) + (B if self.lora is not None else 0) + 1,
                          np.int32)
        packed[:B] = -1
        packed[:n] = positions
        packed[B : B + B * MP] = pt.ravel()
        if self.lora is not None and adapters:
            packed[B + B * MP : B + B * MP + len(adapters)] = adapters
        packed[-1] = step

        if isinstance(tokens, jax.Array):
            if tokens.shape[0] != B:
                raise ValueError(
                    f"chained token array has batch {tokens.shape[0]}, "
                    f"dispatch bucket is {B} — chaining requires a stable "
                    "bucket (sync to host when the batch re-buckets)"
                )
            tok = tokens  # pass through untouched: no eager slice programs
        else:
            tok_h = np.zeros(B, np.int32)
            tok_h[:n] = tokens
            with self._allow("decode_staging"):
                tok = jnp.asarray(tok_h)

        hist = None
        if histories is not None:
            # bucketed so history growth re-compiles per bucket, not per
            # token; pad token == vocab_size scatters drop in _decode_loop
            H = max(8, max((len(h) for h in histories), default=1))
            H = -(-H // 128) * 128
            hist_h = np.full((B, H), self.config.vocab_size, np.int32)
            plen_h = np.zeros(B, np.int32)
            for i, h in enumerate(histories):
                hist_h[i, : len(h)] = h
                plen_h[i] = (
                    prompt_lens[i] if prompt_lens is not None else len(h)
                )
            with self._allow("decode_staging"):
                hist = (jnp.asarray(hist_h), jnp.asarray(plen_h))

        mask_dev = None
        if masks is not None:
            m = np.ones((B, self.config.vocab_size), bool)
            m[: masks.shape[0]] = masks  # pad rows stay all-allowed
            with self._allow("decode_staging"):
                mask_dev = jnp.asarray(m)

        if self.pp:
            if n_logprobs >= 0 or hist is not None or biases is not None \
                    or mask_fn is not None or guided_dev is not None:
                raise NotImplementedError(
                    "logprobs/penalties/logit_bias/multi-step guided masks "
                    "are not wired on the pipeline-parallel decode path yet"
                )
            with self._allow("decode_staging"):
                packed_dev = jnp.asarray(packed)
                samp = self._device_sampling(sampling, B)
            toks, last, self.k_pool, self.v_pool = self._jit_pp_decode(
                n_steps, self.params, tok, packed_dev, mask_dev,
                self.k_pool, self.v_pool, samp,
            )
            return toks, last

        bias_dev = None
        if biases is not None:
            bz = np.zeros((B, self.config.vocab_size), np.float32)
            bz[: biases.shape[0]] = biases  # pad rows stay unbiased
            with self._allow("decode_staging"):
                bias_dev = jnp.asarray(bz)

        mkw = {}
        if mask_fn is not None:
            mask_fn.B = B  # callback mask rows must match the padded bucket
            self.set_guided_ctx(mask_fn)
            mkw["mask_fn"] = self._mask_tramp
        elif guided_dev is not None:
            mkw["guided"] = self._guided_op(guided_dev, B)
        with self._allow("decode_staging"):
            packed_dev = jnp.asarray(packed)
            samp = self._device_sampling(sampling, B)
        toks, last, lp, self.k_pool, self.v_pool = self._jit_decode_loop(
            n_steps, n_logprobs, self.params, tok, packed_dev, hist,
            mask_dev, bias_dev, self.k_pool, self.v_pool,
            samp, self.lora, **mkw,
        )
        if n_logprobs >= 0:
            return toks, last, lp
        return toks, last

    def decode_multi_with_prefill(
        self,
        n_steps: int,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        sampling,
        step: int,
        chunk_tokens: List[int],
        chunk_start: int,
        chunk_table: List[int],
        chunk_prior: int,
        adapters: Optional[List[int]] = None,
        chunk_adapter: int = 0,
        masks: Optional[np.ndarray] = None,
        mask_fn=None,
        biases: Optional[np.ndarray] = None,
        guided_dev=None,
    ) -> Tuple[np.ndarray, jax.Array]:
        """Fused mixed iteration (_mixed_loop): the decode batch's fused
        n_steps AND one bounded prefill chunk in a single dispatch.
        Returns (sampled [B_bucket, n_steps] host, chunk last-token
        logits [V] device). The engine falls back to the two-dispatch
        path for feature planes this doesn't carry (logprobs/penalties/
        guided masks/spec decode/multimodal chunks/PP meshes)."""
        if self.pp:
            raise NotImplementedError("fused mixed step has no PP path")
        if self._use_ragged(len(positions), 1):
            chunk = {
                "tokens": chunk_tokens, "start": chunk_start,
                "table": chunk_table, "prior": chunk_prior,
                "adapter": chunk_adapter,
            }
            try:
                toks, chunk_logits = self._decode_multi_with_prefills_ragged(
                    n_steps, tokens, positions, page_tables, sampling,
                    step, [chunk], masks=masks, mask_fn=mask_fn,
                    biases=biases, guided_dev=guided_dev,
                )
                return toks, chunk_logits[0]
            except BucketOverflowError as e:
                if masks is not None or mask_fn is not None \
                        or biases is not None or guided_dev is not None:
                    raise
                log.warning(
                    "mixed plan (%d tokens) overflows ragged T buckets "
                    "(largest %d); using the padded fallback", e.n, e.largest,
                )
        elif masks is not None or mask_fn is not None or biases is not None \
                or guided_dev is not None:
            raise NotImplementedError(
                "guided masks / logit bias require the ragged mixed path"
            )
        ptok, ppos, ppt, pkvl, n = self._prep_prefill(
            chunk_tokens, chunk_start, chunk_table, chunk_prior
        )
        B = _next_bucket(self.decode_buckets, len(positions))
        pt = self._pad_page_table(page_tables, B)
        MP = pt.shape[1]
        packed = np.zeros(
            B * (1 + MP) + (B if self.lora is not None else 0) + 1, np.int32
        )
        packed[:B] = -1
        packed[: len(positions)] = positions
        packed[B : B + B * MP] = pt.ravel()
        if self.lora is not None and adapters:
            packed[B + B * MP : B + B * MP + len(adapters)] = adapters
        packed[-1] = step
        tok_h = np.zeros(B, np.int32)
        tok_h[: len(positions)] = tokens
        padapter = (
            jnp.asarray([chunk_adapter], jnp.int32)
            if self.lora is not None else None
        )
        toks, _, chunk_logits, self.k_pool, self.v_pool = self._jit_mixed(
            n_steps, self.params, ptok, ppos, ppt, pkvl, jnp.int32(n - 1),
            padapter, jnp.asarray(tok_h), jnp.asarray(packed),
            self.k_pool, self.v_pool, self._device_sampling(sampling, B),
            self.lora,
        )
        return np.asarray(jax.device_get(toks)), chunk_logits

    def _prep_prefill_packed(self, chunks: List[Dict[str, Any]]):
        """Bucket-pad a packed chunk set into ragged [N, S] device inputs,
        one row per chunk (each row's valid tokens are a contiguous run
        from s=0, which is the layout the prefill attention kernels'
        q_start/q_len metadata requires — a flat concatenation of
        segments would break their causal masking). Rows past the real
        chunk count replicate row 0: the duplicate rewrites identical KV
        bytes to the same pages (harmless) and avoids q_len=0 edge cases
        in the kernels; its logits row is discarded by the caller."""
        N = _next_bucket(self.pack_buckets, len(chunks))
        S = _next_bucket(
            self.prefill_buckets, max(len(c["tokens"]) for c in chunks)
        )
        tok = np.zeros((N, S), np.int32)
        pos = np.full((N, S), -1, np.int32)
        kvl = np.zeros(N, np.int32)
        last = np.zeros(N, np.int32)
        adapters = np.zeros(N, np.int32)
        rows = []
        for i in range(N):
            c = chunks[i] if i < len(chunks) else chunks[0]
            n = len(c["tokens"])
            tok[i, :n] = c["tokens"]
            pos[i, :n] = np.arange(c["start"], c["start"] + n)
            kvl[i] = c["prior"] + n
            last[i] = n - 1
            adapters[i] = c.get("adapter") or 0
            rows.append(c["table"])
        pt = self._pad_page_table(rows, N)
        padapter = jnp.asarray(adapters) if self.lora is not None else None
        return (jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(pt),
                jnp.asarray(kvl), jnp.asarray(last), padapter)

    def decode_multi_with_prefills(
        self,
        n_steps: int,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        sampling,
        step: int,
        chunks: List[Dict[str, Any]],  # {"tokens", "start", "table",
        #   "prior", "adapter"} per packed chunk (distinct sequences)
        adapters: Optional[List[int]] = None,
        masks: Optional[np.ndarray] = None,  # [n_dec, V] step-0 guided masks
        mask_fn=None,  # GuidedMaskContext for the fused tail steps 1..n-1
        biases: Optional[np.ndarray] = None,  # [n_dec, V] logit-bias rows
        guided_dev=None,  # device guided DFA plan for the fused tail
    ) -> Tuple[np.ndarray, jax.Array]:
        """Packed fused mixed iteration: the decode batch's fused n_steps
        AND the whole token-budgeted prefill chunk set in a SINGLE
        dispatch (the ragged chunks ride as rows of one [N, S] prefill
        batch). Returns (sampled [B_bucket, n_steps] host, per-chunk
        last-token logits [N_bucket, V] device — row i belongs to
        chunks[i], rows past len(chunks) are padding). Same feature-plane
        limits as decode_multi_with_prefill."""
        if self.pp:
            raise NotImplementedError("fused mixed step has no PP path")
        if self._use_ragged(len(positions), len(chunks)):
            try:
                return self._decode_multi_with_prefills_ragged(
                    n_steps, tokens, positions, page_tables, sampling, step,
                    chunks, masks=masks, mask_fn=mask_fn, biases=biases,
                    guided_dev=guided_dev,
                )
            except BucketOverflowError as e:
                if masks is not None or mask_fn is not None \
                        or biases is not None or guided_dev is not None:
                    # the padded fallback has no mask/bias plane; the
                    # engine sheds chunks and retries rather than dropping
                    # a guided row's constraint or a bias ban
                    raise
                log.warning(
                    "mixed plan (%d tokens) overflows ragged T buckets "
                    "(largest %d); using the padded fallback", e.n, e.largest,
                )
        elif masks is not None or mask_fn is not None or biases is not None \
                or guided_dev is not None:
            raise NotImplementedError(
                "guided masks / logit bias require the ragged mixed path "
                "(the engine's _mixed_fusible gates on it)"
            )
        ptok, ppos, ppt, pkvl, plast, padapter = self._prep_prefill_packed(
            chunks
        )
        B = _next_bucket(self.decode_buckets, len(positions))
        pt = self._pad_page_table(page_tables, B)
        MP = pt.shape[1]
        packed = np.zeros(
            B * (1 + MP) + (B if self.lora is not None else 0) + 1, np.int32
        )
        packed[:B] = -1
        packed[: len(positions)] = positions
        packed[B : B + B * MP] = pt.ravel()
        if self.lora is not None and adapters:
            packed[B + B * MP : B + B * MP + len(adapters)] = adapters
        packed[-1] = step
        tok_h = np.zeros(B, np.int32)
        tok_h[: len(positions)] = tokens
        toks, _, chunk_logits, self.k_pool, self.v_pool = self._jit_mixed(
            n_steps, self.params, ptok, ppos, ppt, pkvl, plast,
            padapter, jnp.asarray(tok_h), jnp.asarray(packed),
            self.k_pool, self.v_pool, self._device_sampling(sampling, B),
            self.lora,
        )
        return np.asarray(jax.device_get(toks)), chunk_logits

    # -- guided sampling masks --------------------------------------------
    def _true_mask(self, rows: int) -> jax.Array:
        """Device-resident all-True [rows, V] sampling mask. The ragged
        step takes the mask as a PERMANENT operand (constant treedef =
        no variant split between guided and free dispatches), so the
        unconstrained common case must not pay a [rows, V] host→device
        transfer per iteration — one cached array per row cap does."""
        hit = self._true_mask_cache.get(rows)
        if hit is None:
            hit = jnp.ones((rows, self.config.vocab_size), jnp.bool_)
            self._true_mask_cache[rows] = hit
        return hit

    def _seg_mask(self, masks: Optional[np.ndarray], seg_cap: int) -> jax.Array:
        """Pad row-aligned guided masks to the sampled-row cap (pad rows
        all-allowed); None = the cached all-True operand."""
        if masks is None:
            return self._true_mask(seg_cap)
        m = np.ones((seg_cap, self.config.vocab_size), bool)
        m[: masks.shape[0]] = masks
        return jnp.asarray(m)

    def _zero_bias(self, rows: int) -> jax.Array:
        """Device-resident all-zero [rows, V] logit bias — the cached
        no-op counterpart of _true_mask for the ragged step's permanent
        bias operand."""
        hit = self._zero_bias_cache.get(rows)
        if hit is None:
            hit = jnp.zeros((rows, self.config.vocab_size), jnp.float32)
            self._zero_bias_cache[rows] = hit
        return hit

    def _seg_bias(self, biases: Optional[np.ndarray], seg_cap: int) -> jax.Array:
        """Pad row-aligned logit-bias rows to the sampled-row cap (pad
        rows zero); None = the cached all-zero operand."""
        if biases is None:
            return self._zero_bias(seg_cap)
        b = np.zeros((seg_cap, self.config.vocab_size), np.float32)
        b[: biases.shape[0]] = biases
        return jnp.asarray(b)

    def _identity_rows(self, seg_cap: int) -> Tuple[jax.Array, jax.Array]:
        """Cached identity (row_seq, row_j) maps: non-verify ragged
        dispatches sample row i with base row i's params and no seed
        fold, so the in-XLA expansion is a no-op gather and both arrays
        stay device-resident across iterations (same rationale as
        _true_mask)."""
        hit = self._row_map_cache.get(seg_cap)
        if hit is None:
            hit = (
                jnp.arange(seg_cap, dtype=jnp.int32),
                jnp.zeros(seg_cap, jnp.int32),
            )
            self._row_map_cache[seg_cap] = hit
        return hit

    def set_guided_ctx(self, ctx) -> None:
        """Install the per-dispatch guided-DFA context the decode loop's
        host callback reads (see _GuidedMaskTrampoline)."""
        self._mask_tramp.ctx = ctx

    def stage_guided_tables(self, tables) -> Tuple[jax.Array, jax.Array, List[int]]:
        """Stage a batch's device guided DFA (combined token-level
        transition + mask tables, guided/device_table.py) and return
        (trans_dev [G, V], mask_dev [G, V], state offsets per table).

        Keyed by the schemas' uids so the combined arrays stay
        device-resident across every dispatch of the same constraint set
        — the whole point of the device path is that NOTHING guided
        moves host→device in the warm loop except the [B] initial-state
        vector. Bounded LRU: admission churn across many distinct
        schema combinations evicts the oldest combination."""
        from dynamo_tpu.guided.device_table import combine_tables

        key = tuple(t.uid for t in tables)
        hit = self._guided_dev_cache.get(key)
        if hit is not None:
            self._guided_dev_cache.move_to_end(key)
            trans_dev, mask_dev, offsets = hit
            return trans_dev, mask_dev, offsets
        trans, mask, offsets = combine_tables(tables)
        with self._allow("decode_staging"):
            trans_dev = jnp.asarray(trans)
            mask_dev = jnp.asarray(mask)
        self._guided_dev_cache[key] = (trans_dev, mask_dev, offsets)
        while len(self._guided_dev_cache) > 32:
            self._guided_dev_cache.popitem(last=False)
        return trans_dev, mask_dev, offsets

    def _guided_op(self, guided_dev, B: int):
        """Materialize a (tables, row_entries, pending) plan into the
        _decode_loop `guided` operand tuple for a B-row bucket: combined
        tables from the staged cache, per-row global initial states (pad
        and unguided rows sit in DEAD), pending as a traced scalar so
        pending-0/1 dispatches share one compiled variant."""
        g_tables, g_rows, g_pend = guided_dev
        trans_dev, gmask_dev, offs = self.stage_guided_tables(g_tables)
        dead = int(trans_dev.shape[0]) - 1
        gs0 = np.full(B, dead, np.int32)
        for i, ent in enumerate(g_rows):
            if ent is not None:
                ti, st = ent
                gs0[i] = offs[ti] + int(st)
        with self._allow("decode_staging"):
            gs0_dev = jnp.asarray(gs0)
            gpend = jnp.int32(1 if g_pend else 0)
        return (trans_dev, gmask_dev, gs0_dev, gpend)

    # -- ragged flat-token mixed path -------------------------------------
    def _use_ragged(self, n_decode: int, n_chunks: int) -> bool:
        from dynamo_tpu.ops.ragged_paged_attention import RAGGED_MAX_SEGS

        return (
            self.ragged_mixed
            and n_decode + n_chunks <= RAGGED_MAX_SEGS
        )

    def ensure_ragged_bucket(self, t: int) -> None:
        """Insert an exact T bucket (rounded up to the q-block). The
        engine wires the scheduler's mixed_prefill_tokens + max decode
        batch here at startup, so the token budget IS the compile bucket
        and a full mixed iteration never rounds up to the next power of
        two."""
        qb = self.ragged_q_block
        t = max(qb, -(-int(t) // qb) * qb)
        if t not in self.ragged_buckets:
            self.ragged_buckets = tuple(sorted(set(self.ragged_buckets) | {t}))

    def _prep_ragged(
        self,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        chunks: List[Dict[str, Any]],
    ):
        """Flatten one mixed plan — the decode batch (q_len=1 segments,
        first) + the packed prefill chunks — into a single [T_bucket]
        token axis with the kernel/model metadata from
        build_ragged_metadata. T is the TRUE token sum (no per-segment
        alignment padding): 1x512 + 3x32 chunks + 4 decode rows cost 612
        tokens, not 4x512 padded rows. Raises BucketOverflowError past
        the largest T bucket (the engine sheds chunks and retries)."""
        from dynamo_tpu.ops.ragged_paged_attention import build_ragged_metadata

        n_dec = len(positions)
        q_lens = [1] * n_dec + [len(c["tokens"]) for c in chunks]
        q_starts = list(positions) + [c["start"] for c in chunks]
        kv_lens = [p + 1 for p in positions] + [
            c["prior"] + len(c["tokens"]) for c in chunks
        ]
        rows = list(page_tables) + [c["table"] for c in chunks]
        t_real = sum(q_lens)
        t_bucket = _next_bucket(self.ragged_buckets, t_real)
        md = build_ragged_metadata(
            q_lens, q_starts, kv_lens, rows, t_bucket,
            q_block=self.ragged_q_block, max_pages=self.max_pages_per_seq,
        )
        seg_cap = md["seg_page_table"].shape[0]
        flat = np.zeros(t_bucket, np.int32)
        flat[:n_dec] = tokens
        off = n_dec
        for c in chunks:
            flat[off : off + len(c["tokens"])] = c["tokens"]
            off += len(c["tokens"])
        gather = np.zeros(seg_cap, np.int32)
        gather[: n_dec + len(chunks)] = md["last_index"]
        return (
            jnp.asarray(flat[None]),
            jnp.asarray(md["tok_positions"])[None],
            jnp.asarray(md["tok_page_table"]),
            jnp.asarray(md["tok_kv_lens"]),
            jnp.asarray(md["seg_page_table"]),
            jnp.asarray(md["seg_kv_lens"]),
            jnp.asarray(md["meta"]),
            jnp.asarray(gather),
            seg_cap,
        )

    def _decode_multi_with_prefills_ragged(
        self,
        n_steps: int,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        sampling,
        step: int,
        chunks: List[Dict[str, Any]],
        masks: Optional[np.ndarray] = None,
        mask_fn=None,
        biases: Optional[np.ndarray] = None,
        guided_dev=None,  # device guided DFA plan (decode_multi_async):
        # step 0 rides the ragged mask operand (`masks`), the fused tail
        # rides the in-XLA advance with pending=True (tok0 was sampled
        # on device and is not yet folded into the row states)
    ) -> Tuple[np.ndarray, jax.Array]:
        """Ragged mixed iteration, two dispatches with T-bucket-only and
        decode-bucket-only compile keys respectively:
        1. _ragged_step: flat forward over [T] (decode step 0 + all
           chunks) + last-token gather + sampling at SEG_CAP rows;
        2. steps 1..n-1 through the UNCHANGED _decode_loop, chained on
           the step-0 tokens (positions/step advanced by one, so row
           seeds and step indices match the legacy fused loop exactly).
        Returns the same (sampled [B_bucket, n_steps] host, chunk logits
        [N, V] device) contract as decode_multi_with_prefills."""
        n_dec = len(positions)
        (ftok, fpos, tok_pt, tok_kvl, seg_pt, seg_kvl, meta, gather,
         seg_cap) = self._prep_ragged(tokens, positions, page_tables, chunks)
        row_seq, row_j = self._identity_rows(seg_cap)
        sampled, seg_logits, self.k_pool, self.v_pool = self._jit_ragged(
            self.params, ftok, fpos, tok_pt, tok_kvl, seg_pt, seg_kvl,
            meta, gather, self.k_pool, self.v_pool,
            self._device_sampling(sampling, seg_cap), row_seq, row_j,
            jnp.int32(step),
            self._seg_mask(masks, seg_cap),
            self._seg_bias(biases, seg_cap),
        )
        B = _next_bucket(self.decode_buckets, n_dec)
        tok0 = sampled[:B]  # decode rows lead the segment order
        if n_steps > 1:
            pt = self._pad_page_table(page_tables, B)
            MP = pt.shape[1]
            packed = np.zeros(B * (1 + MP) + 1, np.int32)
            packed[:B] = -1
            packed[:n_dec] = [p + 1 for p in positions]
            packed[B : B + B * MP] = pt.ravel()
            packed[-1] = step + 1
            mkw = {}
            if mask_fn is not None:
                # guided rows continue through the fused tail: the host
                # callback advances each DFA copy by tok0 (still device-
                # resident here) before masking inner step 0
                mask_fn.B = B
                self.set_guided_ctx(mask_fn)
                mkw["mask_fn"] = self._mask_tramp
            elif guided_dev is not None:
                g_tables, g_rows, _ = guided_dev
                mkw["guided"] = self._guided_op((g_tables, g_rows, True), B)
            bias_dev = None
            if biases is not None:
                bz = np.zeros((B, self.config.vocab_size), np.float32)
                bz[: biases.shape[0]] = biases
                bias_dev = jnp.asarray(bz)
            # n_steps is the scheduler's fixed multi-step count, so
            # n_steps-1 adds exactly ONE decode_loop variant alongside the
            # legacy path's n_steps — bounded by design (ragged two-
            # dispatch split, docs/ragged_attention.md)
            rest, _, _, self.k_pool, self.v_pool = self._jit_decode_loop(  # dynlint: disable=DYN-J004
                n_steps - 1, -1, self.params, tok0, jnp.asarray(packed),
                None, None, bias_dev, self.k_pool, self.v_pool,
                self._device_sampling(sampling, B), None, **mkw,
            )
            tok0_h, rest_h = jax.device_get((tok0, rest))
            toks = np.concatenate(
                [np.asarray(tok0_h)[:, None], np.asarray(rest_h)], axis=1
            )
        else:
            toks = np.asarray(jax.device_get(tok0))[:, None]
        chunk_logits = seg_logits[n_dec : n_dec + len(chunks)]  # [N, V]
        return toks, chunk_logits

    def verify_spec(
        self,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        drafts: List[List[int]],
        sampling,
        step: int,
        chunks: Sequence[Dict[str, Any]] = (),
        masks: Optional[Dict[int, np.ndarray]] = None,  # row index ->
        # [V] bool guided mask for that row's single verify position
        # (guided rows never draft, so exactly one position each)
        biases: Optional[Dict[int, np.ndarray]] = None,  # row index ->
        # [V] f32 logit-bias row, same draft-less single-position contract
    ) -> Tuple[List[np.ndarray], jax.Array]:
        """One speculative-verify iteration through the SAME _jit_ragged
        program as the mixed path — zero new compile families or
        variants, by construction.

        Each speculating sequence contributes ONE segment of q_len
        len(draft)+1 to the flat [T] axis (its last real token followed
        by the drafted tokens); packed prefill chunks ride behind as
        usual. The gather array is content-only (not a shape), so
        instead of one last-token entry per segment it carries an entry
        for EVERY verify position — the kernel's causal masking already
        gives each flat token its correct prefix logits (chunked prefill
        depends on the same property), and sampling at SEG_CAP rows
        covers them all. Verify position j>0 folds j into the row seed
        so positions draw independent randomness (temperature-0 is
        argmax and unaffected — greedy byte-identity holds).

        KV for the fed draft tokens lands at positions
        computed_len..computed_len+K as a side effect; the engine
        commits a prefix of it simply by advancing computed_len per
        accepted token (stale suffix KV is overwritten or never read —
        kv_len masking), so rollback is free and page/hash lineage only
        ever covers committed tokens.

        Returns (rows, chunk_logits): rows[i] is the np token vector of
        length len(drafts[i])+1 sampled from the TARGET distribution at
        each verify position; chunk_logits are the packed chunks' last-
        token logits, device-resident, same contract as the mixed path.
        Raises BucketOverflowError when the plan exceeds the T bucket or
        the gather capacity (defensive — the scheduler budgets drafted
        tokens against both)."""
        from dynamo_tpu.ops.ragged_paged_attention import (
            RAGGED_MAX_SEGS, build_ragged_metadata, ragged_seg_cap,
        )

        chunks = list(chunks)
        n_rows = len(positions)
        row_lens = [len(d) + 1 for d in drafts]
        q_lens = row_lens + [len(c["tokens"]) for c in chunks]
        q_starts = list(positions) + [c["start"] for c in chunks]
        kv_lens = [p + ln for p, ln in zip(positions, row_lens)] + [
            c["prior"] + len(c["tokens"]) for c in chunks
        ]
        rows = list(page_tables) + [c["table"] for c in chunks]
        n_seg = len(q_lens)
        t_real = sum(q_lens)
        t_bucket = _next_bucket(self.ragged_buckets, t_real)
        seg_cap = ragged_seg_cap(t_bucket)
        entries = sum(row_lens) + len(chunks)
        if n_seg > RAGGED_MAX_SEGS or entries > seg_cap:
            raise BucketOverflowError(max(n_seg, entries), (seg_cap,))
        md = build_ragged_metadata(
            q_lens, q_starts, kv_lens, rows, t_bucket,
            q_block=self.ragged_q_block, max_pages=self.max_pages_per_seq,
        )
        flat = np.zeros(t_bucket, np.int32)
        off = 0
        for tok, d in zip(tokens, drafts):
            flat[off] = tok
            flat[off + 1 : off + 1 + len(d)] = d
            off += len(d) + 1
        for c in chunks:
            flat[off : off + len(c["tokens"])] = c["tokens"]
            off += len(c["tokens"])
        cu = md["cu_q_lens"]
        gather = np.zeros(seg_cap, np.int32)
        w = 0
        for i in range(n_rows):
            gather[w : w + row_lens[i]] = np.arange(cu[i], cu[i + 1])
            w += row_lens[i]
        chunk_entry0 = w
        for s in range(n_rows, n_seg):
            gather[w] = cu[s + 1] - 1
            w += 1
        # per-entry sampling expansion happens IN-XLA (_ragged_step's
        # row_seq/row_j gather+seed-fold): the staged base is the per-
        # SEQUENCE params — stable across verify iterations, so
        # _device_sampling cache-hits instead of rebuilding + re-staging
        # a fresh per-entry expansion every dispatch. Chunk (and pad)
        # entries point at a padding base row: greedy, seed 0 — exactly
        # the params the host expansion gave them.
        row_seq = np.zeros(seg_cap, np.int32)
        row_j = np.zeros(seg_cap, np.int32)
        w2 = 0
        for i in range(n_rows):
            row_seq[w2 : w2 + row_lens[i]] = i
            row_j[w2 : w2 + row_lens[i]] = np.arange(row_lens[i])
            w2 += row_lens[i]
        # chunk entries (and trailing pad rows) sample with padding
        # params; n_rows < seg_cap whenever chunk entries exist (entries
        # = sum(row_lens) + len(chunks) <= seg_cap and row_lens >= 1)
        row_seq[w2:] = min(n_rows, seg_cap - 1)
        row_masks = None
        if masks:
            # guided rows ride the verify dispatch as draft-less q_len=1
            # segments (per-sequence speculation pause): mask only their
            # verify position, every other entry stays all-allowed
            row_masks = np.ones(
                (sum(row_lens), self.config.vocab_size), bool
            )
            offs = np.concatenate([[0], np.cumsum(row_lens)])
            for i, m in masks.items():
                row_masks[offs[i]] = m
        row_biases = None
        if biases:
            row_biases = np.zeros(
                (sum(row_lens), self.config.vocab_size), np.float32
            )
            offs = np.concatenate([[0], np.cumsum(row_lens)])
            for i, b in biases.items():
                row_biases[offs[i]] = b
        with self._allow("verify_staging"):
            staged = (
                jnp.asarray(flat[None]),
                jnp.asarray(md["tok_positions"])[None],
                jnp.asarray(md["tok_page_table"]),
                jnp.asarray(md["tok_kv_lens"]),
                jnp.asarray(md["seg_page_table"]),
                jnp.asarray(md["seg_kv_lens"]),
                jnp.asarray(md["meta"]),
                jnp.asarray(gather),
            )
            samp = self._device_sampling(sampling, seg_cap)
            row_seq_d = jnp.asarray(row_seq)
            row_j_d = jnp.asarray(row_j)
            step_d = jnp.int32(step)
            seg_mask = self._seg_mask(row_masks, seg_cap)
            seg_bias = self._seg_bias(row_biases, seg_cap)
        sampled, seg_logits, self.k_pool, self.v_pool = self._jit_ragged(
            self.params, *staged,
            self.k_pool, self.v_pool,
            samp, row_seq_d, row_j_d, step_d, seg_mask, seg_bias,
        )
        with self._allow("token_readback"):
            sampled_h = np.asarray(jax.device_get(sampled))  # one bulk sync
        out: List[np.ndarray] = []
        w = 0
        for ln in row_lens:
            out.append(sampled_h[w : w + ln])
            w += ln
        if chunks:
            # slicing with host ints stages them as dynamic-slice starts;
            # that is dispatch staging, same budget as the operand block
            with self._allow("verify_staging"):
                chunk_logits = seg_logits[
                    chunk_entry0 : chunk_entry0 + len(chunks)
                ]
        else:
            chunk_logits = []  # no slice at all: a zero-length take would
            # still stage its bounds and trip the strict transfer guard
        return out, chunk_logits

    # -- device n-gram draft ring ------------------------------------------
    def ensure_draft_ring(
        self, slots: int, k: int, window: int = DRAFT_RING_WINDOW,
    ) -> int:
        """Allocate the device draft ring ([slots, window] history + per-
        slot lengths) and WARM the draft jit — compile happens here, at
        engine-enable time, never inside the warm loop (the sanitizer's
        recompile tripwire freezes family variants after warmup).
        Returns the per-iteration delta capacity D: the engine resets a
        slot (host-mirror rewrite + cold restage) when a sequence
        commits more than D tokens between proposals."""
        D = max(16, int(k) + 2)
        shape = (int(slots), int(window), D)
        if self._draft_ring_shape == shape and self._draft_ring is not None:
            return D
        hist = np.full((slots, window), -1, np.int32)
        lens = np.zeros(slots, np.int32)
        self._draft_ring_host = (hist, lens)
        with self._allow("spec_staging"):
            self._draft_ring = (jnp.asarray(hist), jnp.asarray(lens))
            zt = jnp.full((slots, D), -1, jnp.int32)
            zn = jnp.zeros(slots, jnp.int32)
        h, l = self._draft_ring
        h, l, _, _ = self._jit_draft_ring(h, l, zt, zn, int(k))
        self._draft_ring = (h, l)
        self._draft_ring_dirty = False
        self._draft_ring_shape = shape
        return D

    def draft_ring_reset(self, slot: int, tokens: Sequence[int]) -> None:
        """Rewrite one slot's history (admission, slot reuse, or a delta
        too large for the append bucket) in the HOST mirror; the next
        draft_step restages the whole ring — cold-path by construction,
        the warm loop only ever appends deltas."""
        hist, lens = self._draft_ring_host
        W = hist.shape[1]
        tail = list(tokens)[-W:]
        hist[slot] = -1
        hist[slot, : len(tail)] = tail
        lens[slot] = len(tail)
        self._draft_ring_dirty = True

    def draft_step(
        self, updates: Sequence[Tuple[int, Sequence[int]]], k: int,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """One fused device draft step: append each (slot, delta) to the
        ring and propose k continuation tokens per slot (see
        _draft_ring_step). Stages only the [SLOTS, D] delta; the
        proposal readback is the loop's single draft-side host touch
        (sanitizer label draft_readback). Returns (drafts [SLOTS, k],
        n_prop [SLOTS]) host arrays."""
        slots, W, D = self._draft_ring_shape
        hist_h, lens_h = self._draft_ring_host
        upd_tok = np.full((slots, D), -1, np.int32)
        upd_n = np.zeros(slots, np.int32)
        for slot, delta in updates:
            d = list(delta)
            assert len(d) <= D, "draft delta exceeds ring bucket (reset)"
            upd_tok[slot, : len(d)] = d
            upd_n[slot] = len(d)
            # mirror the append so a later reset/restage stays coherent
            n = len(d)
            if lens_h[slot] + n > W:
                over = lens_h[slot] + n - W
                hist_h[slot, : W - over] = hist_h[slot, over:]
                lens_h[slot] -= over
            hist_h[slot, lens_h[slot] : lens_h[slot] + n] = d
            lens_h[slot] += n
        with self._allow("spec_staging"):
            if self._draft_ring_dirty:
                # cold restage after slot resets: the device ring is
                # rebuilt from the mirror (deltas above are already in
                # the mirror, so stage ZERO updates this round)
                self._draft_ring = (jnp.asarray(hist_h), jnp.asarray(lens_h))
                self._draft_ring_dirty = False
                upd_tok[:] = -1
                upd_n[:] = 0
            ut = jnp.asarray(upd_tok)
            un = jnp.asarray(upd_n)
        h, l = self._draft_ring
        h, l, drafts, n_prop = self._jit_draft_ring(h, l, ut, un, int(k))
        self._draft_ring = (h, l)
        with self._allow("draft_readback"):
            d_h, n_h = jax.device_get((drafts, n_prop))
        return np.asarray(d_h), np.asarray(n_h)

    def compile_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per step-function family: compiled-variant count, cumulative
        compile seconds, call count. Ships as worker gauges
        (worker_common) and the goodput report's extras["compile"] so
        the ragged path's cache-cardinality collapse is a CI artifact,
        not a claim."""
        return {name: fam.stats() for name, fam in self._families.items()}

    def _device_sampling(self, sampling, B: int) -> SamplingParams:
        """Device-resident cache of padded sampling params. Batches resend
        identical sampling lists every dispatch; materializing them fresh
        costs several host→device transfers per dispatch (each a full relay
        round trip). SamplingParams instances pass through (assumed already
        on device and bucket-sized by the caller)."""
        if isinstance(sampling, SamplingParams):
            return _pad_sampling(sampling, B)
        n = len(sampling["temperature"])
        rep = list(sampling.get("rep") or [1.0] * n)
        freq = list(sampling.get("freq") or [0.0] * n)
        presence = list(sampling.get("presence") or [0.0] * n)
        key = (
            B,
            tuple(sampling["temperature"]),
            tuple(sampling["top_k"]),
            tuple(sampling["top_p"]),
            tuple(sampling["seeds"]),
            tuple(rep), tuple(freq), tuple(presence),
        )
        hit = self._sampling_cache.get(key)
        if hit is None:
            pad = B - n
            hit = SamplingParams.make(
                temperature=list(sampling["temperature"]) + [0.0] * pad,
                top_k=list(sampling["top_k"]) + [0] * pad,
                top_p=list(sampling["top_p"]) + [1.0] * pad,
                seeds=list(sampling["seeds"]) + [0] * pad,
                rep_penalty=rep + [1.0] * pad,
                freq_penalty=freq + [0.0] * pad,
                presence_penalty=presence + [0.0] * pad,
            )
            if len(self._sampling_cache) >= 512:
                self._sampling_cache.clear()
            self._sampling_cache[key] = hit
        return hit

    def reload_params(self, path: str) -> None:
        """Swap the serving weights from an orbax snapshot IN PLACE (the
        RL weight-update path, reference lib/rl role: policy weights
        refresh between rollouts without restarting the worker). The
        jitted step functions take params as an argument, so the swap is
        just a device_put with the same shardings — no recompilation."""
        from dynamo_tpu.engine.weights import load_orbax

        new = load_orbax(path)
        new = jax.tree.map(jnp.asarray, new)
        if self.quantize in ("int8", "fp8"):
            # the jitted step fns were traced against the QUANTIZED tree
            # (scale leaves, int8 dtypes) — a raw tree would retrace/crash
            from dynamo_tpu.models.quant import quantize_params

            new = quantize_params(new, mode=self.quantize, donate=True)
        self.params = jax.device_put(
            new, self.policy.params_sharding(new)
        )

    @property
    def has_draft(self) -> bool:
        return self.draft_config is not None

    # -- multi-LoRA registry ------------------------------------------------
    @property
    def adapter_names(self) -> List[str]:
        return list(self._adapter_slots)

    def register_adapter(self, name: str, factors: Dict[str, Any]) -> int:
        """Install an adapter's factors into the next free slot; returns
        the slot index sequences reference. factors: models/lora.py layout
        ({t}_a [L,in,r], {t}_b [L,r,out], scaling folded into B)."""
        from dynamo_tpu.models import lora as lora_mod

        if self.lora is None:
            raise RuntimeError("runner built without lora_slots")
        if name in self._adapter_slots:
            return self._adapter_slots[name]
        slot = len(self._adapter_slots) + 1  # 0 is the base slot
        n_slots = next(iter(self.lora["layers"].values())).shape[1]
        if slot >= n_slots:
            raise RuntimeError(f"all {n_slots - 1} LoRA slots in use")
        self.lora = lora_mod.set_adapter_slot(self.lora, slot, factors)
        self._adapter_slots[name] = slot
        log.info("registered LoRA adapter %r in slot %d", name, slot)
        return slot

    def adapter_slot(self, name: Optional[str]) -> int:
        if not name:
            return 0
        return self._adapter_slots[name]

    def spec_decode_multi(
        self,
        n_rounds: int,
        tokens: List[int],
        positions: List[int],
        page_tables: List[List[int]],
        sampling,
        step: int,
        gamma: Optional[int] = None,
        adapters: Optional[List[int]] = None,
    ):
        """n_rounds fused speculative rounds (one host sync). Returns
        (tokens [B_bucket, R, gamma+1], counts [B_bucket, R]); row i's
        round r contributes counts[i, r] valid tokens. Page tables must
        cover positions[i] + n_rounds*(gamma+1) slots. `gamma` overrides
        the configured draft length (the engine shrinks it near token
        budgets so the draft pool never gaps)."""
        gamma = self.spec_gamma if gamma is None else gamma
        n = len(tokens)
        B = _next_bucket(self.decode_buckets, n)
        tok = np.zeros(B, np.int32)
        tok[:n] = tokens
        pos = np.full(B, -1, np.int32)
        pos[:n] = positions
        pt = self._pad_page_table(page_tables, B)

        with self._allow("spec_staging"):
            tok_d, pos_d, pt_d = jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(pt)
            samp = self._device_sampling(sampling, B)
            step_d = jnp.int32(step)
            adapt_d = self._adapter_array(adapters, B)
        toks, counts, self.k_pool, self.v_pool, self.draft_k_pool, self.draft_v_pool = (
            self._jit_spec(
                gamma, n_rounds, self.params, self.draft_params,
                tok_d, pos_d,
                self.k_pool, self.v_pool, self.draft_k_pool, self.draft_v_pool,
                pt_d, samp, step_d, self.lora, adapt_d,
            )
        )
        with self._allow("token_readback"):
            toks_h, counts_h = jax.device_get((toks, counts))
        return np.asarray(toks_h), np.asarray(counts_h)

    def draft_prefill(
        self,
        tokens: List[int],
        start_pos: int,
        page_table_row: List[int],
        prior_len: int,
        mm: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Prefill the DRAFT model's KV pools for a chunk (same page
        table as the target). Logits are discarded — only the KV matters
        for later proposals. mm is injected only when the draft's hidden
        size matches (otherwise proposals just degrade, never correctness)."""
        tok, pos, pt, kv_lens, n = self._prep_prefill(tokens, start_pos, page_table_row, prior_len)
        mm_embeds = mm_mask = None
        if mm is not None and self.draft_config.dim == self.config.dim:
            mm_embeds, mm_mask = self._mm_arrays(mm, tok.shape[1])
        _, self.draft_k_pool, self.draft_v_pool = self._jit_draft_forward(
            self.draft_params, tok, pos, self.draft_k_pool, self.draft_v_pool,
            pt, kv_lens, jnp.int32(n - 1), attn_impl=self.attn_impl,
            mesh=self._fwd_mesh, mm_embeds=mm_embeds, mm_mask=mm_mask,
        )

    def sample_one(self, logits: jax.Array, sampling, step: int,
                   mask: Optional[np.ndarray] = None,
                   bias: Optional[np.ndarray] = None) -> int:
        out = self._jit_sample(
            logits[None, :], _as_sampling(sampling), jnp.int32(step),
            mask=jnp.asarray(mask[None, :]) if mask is not None else None,
            bias=jnp.asarray(bias[None, :]) if bias is not None else None,
        )
        return int(jax.device_get(out)[0])

    def sample_one_ex(
        self,
        logits: jax.Array,
        sampling,
        step: int,
        history: Optional[List[int]] = None,
        n_logprobs: int = -1,
        mask: Optional[np.ndarray] = None,
        bias: Optional[np.ndarray] = None,
    ):
        """sample_one with penalties (over `history` token ids) and/or a
        logprob report. Returns (token, lp) where lp is None or
        (tok_lp, top_ids list, top_lps list) for the sampled position."""
        if not hasattr(self, "_jit_sample_one_ex"):
            self._jit_sample_one_ex = jax.jit(
                partial(_sample_one_ex, self.config.vocab_size),
                static_argnums=(0,),
            )
        hist = None
        if history is not None:
            H = -(-max(1, len(history)) // 128) * 128
            h = np.full(H, self.config.vocab_size, np.int32)
            h[: len(history)] = history
            hist = jnp.asarray(h)
        out = self._jit_sample_one_ex(
            n_logprobs, logits, hist, _as_sampling(sampling), jnp.int32(step),
            jnp.asarray(mask[None, :]) if mask is not None else None,
            jnp.asarray(bias[None, :]) if bias is not None else None,
        )
        out = jax.device_get(out)
        tok = int(out[0][0])
        if n_logprobs < 0:
            return tok, None
        tok_lp, ids, vals = out[1], out[2], out[3]
        return tok, (
            float(tok_lp[0]),
            [int(i) for i in ids[0]],
            [float(v) for v in vals[0]],
        )

    def _pad_page_table(self, rows: List[List[int]], B: Optional[int] = None) -> np.ndarray:
        B = B or len(rows)
        pt = np.zeros((B, self.max_pages_per_seq), np.int32)
        for i, row in enumerate(rows):
            pt[i, : len(row)] = row
        return pt

    def embed(self, token_lists: List[List[int]]) -> np.ndarray:
        """Batched embedding forward → [n, E] float32 (L2-normalized)."""
        if not hasattr(self, "_jit_encode"):
            self._jit_encode = jax.jit(partial(llama.encode, self.config))
        n = len(token_lists)
        B = _next_bucket(self.decode_buckets, n)
        S = _next_bucket(self.prefill_buckets, max(len(t) for t in token_lists))
        toks = np.zeros((B, S), np.int32)
        lens = np.zeros(B, np.int32)
        for i, t in enumerate(token_lists):
            toks[i, : len(t)] = t
            lens[i] = len(t)
        out = self._jit_encode(self.params, jnp.asarray(toks), jnp.asarray(lens))
        return np.asarray(jax.device_get(out))[:n]

    # -- disagg KV transfer: device-resident path (colocated P/D) ----------
    # Transfer/offload boundary contract: pages always cross it DENSE (the
    # pool dtype, normally bf16) regardless of kv_quantize — host tiers,
    # the disagg wire format and peer workers see one layout, so quantized
    # and unquantized workers interoperate. Export dequantizes, import
    # re-quantizes (per-vector scales are recomputed; error is one extra
    # rounding, bounded by the int8 step).
    def _dense_pages(self, pool, idx):
        # token-major pools: page axis 1 for every representation
        if isinstance(pool, dict):
            from dynamo_tpu.models.quant import kv_pool_dequantize

            sel = jax.tree.map(lambda a: a[:, idx], pool)
            return kv_pool_dequantize(sel, dtype=self.dtype)
        if self._kv_copy_kernel:
            from dynamo_tpu.ops.block_copy import (
                gather_pages,
                gather_pages_sharded,
            )

            if self._kv_copy_sharded:
                return gather_pages_sharded(
                    pool, idx, self.mesh, interpret=self._kv_copy_interpret
                )
            return gather_pages(pool, idx, interpret=self._kv_copy_interpret)
        return pool[:, idx]

    def _store_pages(self, pool, idx, dense):
        if isinstance(pool, dict):
            from dynamo_tpu.models.quant import kv_pool_quantize

            d = kv_pool_quantize(dense)
            return jax.tree.map(lambda a, u: a.at[:, idx].set(u), pool, d)
        if self._kv_copy_kernel:
            from dynamo_tpu.ops.block_copy import (
                scatter_pages,
                scatter_pages_sharded,
            )

            if self._kv_copy_sharded:
                return scatter_pages_sharded(
                    pool, idx, dense.astype(pool.dtype), self.mesh,
                    interpret=self._kv_copy_interpret,
                )
            return scatter_pages(pool, idx, dense.astype(pool.dtype),
                                 interpret=self._kv_copy_interpret)
        return pool.at[:, idx].set(dense)

    def export_pages_device(self, pages: List[int]):
        """Gather whole KV pages into fresh device buffers (no host copy).
        The gather materializes a new array, so the source pool can keep
        being donated by its engine's step loop afterwards."""
        idx = jnp.asarray(np.asarray(pages, np.int32))
        return self._dense_pages(self.k_pool, idx), self._dense_pages(self.v_pool, idx)

    def import_pages_device(self, target_pages: List[int], offset: int, k, v) -> None:
        """Scatter device-staged pages into this pool's slots (the TPU
        analog of the reference's NIXL device-to-device transfer; the
        host-staged path below is the DCN fallback)."""
        idx = jnp.asarray(np.asarray(target_pages, np.int32))
        n = len(target_pages)
        self.k_pool = self._store_pages(self.k_pool, idx, k[:, offset : offset + n])
        self.v_pool = self._store_pages(self.v_pool, idx, v[:, offset : offset + n])

    def copy_pages(self, src: int, dst: int) -> None:
        """Fork-on-branch CoW: duplicate one page's KV into a fresh slot
        so a branch can diverge without clobbering the sibling's partial
        tail page. One jitted donated program (src/dst are traced
        scalars — a single compile serves every fork); quantized dict
        pools copy raw payload+scales, no dequant round-trip. Draft-model
        pools mirror the page table, so a speculating runner copies those
        too."""
        if not hasattr(self, "_jit_copy_page"):
            def _cp(kp, vp, s, d):
                def one(p):
                    if isinstance(p, dict):
                        return jax.tree.map(
                            lambda a: a.at[:, d].set(a[:, s]), p
                        )
                    return p.at[:, d].set(p[:, s])
                return one(kp), one(vp)
            self._jit_copy_page = jax.jit(_cp, donate_argnums=(0, 1))
        self.k_pool, self.v_pool = self._jit_copy_page(
            self.k_pool, self.v_pool, src, dst
        )
        if getattr(self, "draft_k_pool", None) is not None:
            self.draft_k_pool, self.draft_v_pool = self._jit_copy_page(
                self.draft_k_pool, self.draft_v_pool, src, dst
            )

    # -- disagg KV transfer (host-staged DCN path, SURVEY.md §2.11) ---------
    def export_pages(self, pages: List[int]) -> Dict[str, Any]:
        """Device→host read of whole KV pages for P→D transfer. Layout on
        the wire: [L, n_pages, PS, Hk, D] per pool, raw bytes. On a
        multi-host mesh the gather runs jitted with a replicated output
        sharding (an all-gather over ICI) so every process holds the full
        pages and the host read is local."""
        idx = jnp.asarray(np.asarray(pages, np.int32))
        if self.multihost:
            if not hasattr(self, "_jit_export_repl"):
                from jax.sharding import NamedSharding

                from dynamo_tpu.parallel.mesh import SPEC_REPLICATED

                repl = NamedSharding(self.mesh, SPEC_REPLICATED)
                self._jit_export_repl = jax.jit(
                    lambda kp, vp, i: (
                        self._dense_pages(kp, i), self._dense_pages(vp, i)
                    ),
                    out_shardings=(repl, repl),
                )
            k_d, v_d = self._jit_export_repl(self.k_pool, self.v_pool, idx)
            k = np.asarray(jax.device_get(k_d))
            v = np.asarray(jax.device_get(v_d))
            return kv_arrays_to_payload(k, v, tp=self.mesh_config.model)
        k = np.asarray(jax.device_get(self._dense_pages(self.k_pool, idx)))
        v = np.asarray(jax.device_get(self._dense_pages(self.v_pool, idx)))
        return kv_arrays_to_payload(k, v, tp=self.mesh_config.model)

    @property
    def kv_page_shape(self) -> Tuple[int, int, int, int]:
        """(L, PS, Hk, D) page geometry of this runner's pools — the local
        side of the cross-TP layout handshake. Derived from the ACTUAL
        k-pool shape, so MLA's latent pool (Hk=1, D=d_c+d_rh) advertises
        its real geometry instead of a phantom full-head one."""
        k = self.k_pool["q"] if isinstance(self.k_pool, dict) else self.k_pool
        L, _, PS, Hk, D = k.shape
        return (L, PS, Hk, D)

    @property
    def kv_wire_dtype(self) -> str:
        """Dtype name pages cross the transfer boundary with (the DENSE
        pool dtype — quantized pools dequantize at export)."""
        return str(np.dtype(self.dtype))

    def _store_pages_layers(self, pool, idx, dense, lo: int):
        """Layer-group scatter: write dense [Lg, n, PS, Hk, D] pages into
        pool layers [lo, lo+Lg) at slots idx — the per-group unit of the
        streamed onboard. Quantized pools fold the group on device; the
        block-copy kernel path has a dedicated layer-sliced variant."""
        Lg = int(dense.shape[0])
        if isinstance(pool, dict):
            from dynamo_tpu.models.quant import kv_pool_quantize

            d = kv_pool_quantize(dense)
            return jax.tree.map(
                lambda a, u: a.at[lo : lo + Lg, idx].set(u), pool, d)
        if self._kv_copy_kernel and not self._kv_copy_sharded:
            from dynamo_tpu.ops.block_copy import scatter_pages_layers

            return scatter_pages_layers(
                pool, idx, dense.astype(pool.dtype),
                jnp.asarray([lo], jnp.int32),
                interpret=self._kv_copy_interpret,
            )
        return pool.at[lo : lo + Lg, idx].set(dense.astype(pool.dtype))

    def import_pages(self, target_pages: List[int], offset: int,
                     payload: Dict[str, Any], layer_groups: int = 1) -> None:
        """Host→device write of transferred pages into this pool's page
        slots. `offset` = first payload page to use (earlier pages were
        satisfied by the local prefix cache). Validates the payload's layout
        metadata against the local pool geometry (KvWireLayoutMismatch on
        any divergence); a cross-TP exporter is fine — the dense wire pages
        reshard into this mesh's pool sharding on the scatter below.

        layer_groups > 1 streams the import in contiguous layer slabs
        (FlowKV-style): each group's host staging + device scatter issues
        independently, so the scheduler can dispatch prefill as soon as
        the shallow layers land while deeper groups are still in flight.
        Final pool contents are identical to a whole-sequence import."""
        if payload.get("quant") == "int8_ts":
            return self._import_pages_quant(
                target_pages, offset, payload, layer_groups)
        arrays = kv_payload_to_arrays(payload, self.kv_page_shape, self.kv_wire_dtype)
        if arrays is None:
            return
        k, v = arrays
        sel = slice(offset, offset + len(target_pages))
        idx = jnp.asarray(np.asarray(target_pages, np.int32))
        if layer_groups <= 1:
            self.k_pool = self._store_pages(self.k_pool, idx, jnp.asarray(k[:, sel]))
            self.v_pool = self._store_pages(self.v_pool, idx, jnp.asarray(v[:, sel]))
            return
        L = self.kv_page_shape[0]
        for lo, hi in layer_group_bounds(L, layer_groups):
            self.k_pool = self._store_pages_layers(
                self.k_pool, idx, jnp.asarray(k[lo:hi, sel]), lo)
            self.v_pool = self._store_pages_layers(
                self.v_pool, idx, jnp.asarray(v[lo:hi, sel]), lo)

    def _import_pages_quant(self, target_pages: List[int], offset: int,
                            payload: Dict[str, Any],
                            layer_groups: int = 1) -> None:
        """Native int8+scales import (kv_quant_arrays_to_payload): tier
        blocks already in the device fold land in quantized pools with NO
        dequantize/requantize round trip — zero extra rounding on the
        promotion path. Dense-pool runners dequantize instead (same
        result as the dense wire, one rounding)."""
        if payload.get("layout") != KV_WIRE_LAYOUT_VERSION:
            raise KvWireLayoutMismatch(
                f"kv wire layout {payload.get('layout')} != {KV_WIRE_LAYOUT_VERSION}"
            )
        kq, ks = np.asarray(payload["kq"]), np.asarray(payload["ks"])
        vq, vs = np.asarray(payload["vq"]), np.asarray(payload["vs"])
        L, PS, Hk, D = self.kv_page_shape
        got = (kq.shape[0],) + tuple(kq.shape[2:])
        if got != (L, PS, Hk, D):
            raise KvWireLayoutMismatch(
                f"quant page geometry {got} != local (L={L}, PS={PS}, "
                f"Hk={Hk}, D={D})"
            )
        if not isinstance(self.k_pool, dict):
            from dynamo_tpu.kvbm.quant import dequantize_block

            dt = np.dtype(self.dtype)
            dense = {
                "data": True,
                "k": dequantize_block({"q": kq, "s": ks}, dt).tobytes(),
                "v": dequantize_block({"q": vq, "s": vs}, dt).tobytes(),
                "shape": list(kq.shape), "dtype": str(dt),
                "v_shape": list(vq.shape),
                "n_pages": int(kq.shape[1]),
                "layout": KV_WIRE_LAYOUT_VERSION,
                "page_size": PS, "kv_heads": Hk, "head_dim": D, "layers": L,
                "tp": 1,
            }
            return self.import_pages(target_pages, offset, dense, layer_groups)
        sel = slice(offset, offset + len(target_pages))
        idx = jnp.asarray(np.asarray(target_pages, np.int32))
        for lo, hi in layer_group_bounds(L, max(1, layer_groups)):
            for name, q, s in (("k_pool", kq, ks), ("v_pool", vq, vs)):
                pool = getattr(self, name)
                setattr(self, name, {
                    "q": pool["q"].at[lo:hi, idx].set(jnp.asarray(q[lo:hi, sel])),
                    "s": pool["s"].at[lo:hi, idx].set(jnp.asarray(s[lo:hi, sel])),
                })

    def pools_deleted(self) -> bool:
        """True when the KV pool buffers were consumed by donation into a
        step that then FAILED — the arrays exist as tracers but their
        device memory is gone, and every later step raises."""
        try:
            return any(
                getattr(a, "is_deleted", lambda: False)()
                for a in jax.tree.leaves((self.k_pool, self.v_pool))
            )
        except Exception:
            return True

    def reset_kv_pools(self) -> None:
        """Rebuild zeroed KV pools with the original shapes/sharding (the
        recovery path after pools_deleted()). All cached KV content is
        lost — the caller must also reset its PagePool bookkeeping."""
        k_pool, v_pool = llama.make_kv_pool(
            self.config, self.num_pages, self.page_size, self.dtype,
            kv_quantize=self.kv_quantize,
        )
        sh = self.policy.kv_pool_sharding_tree(k_pool)
        self.k_pool = jax.device_put(k_pool, sh)
        self.v_pool = jax.device_put(v_pool, sh)
        if self.draft_config is not None:
            dk, dv = llama.make_kv_pool(
                self.draft_config, self.num_pages, self.page_size, self.dtype,
                kv_quantize=self.kv_quantize,
            )
            dsh = self.policy.kv_pool_sharding_tree(dk)
            self.draft_k_pool = jax.device_put(dk, dsh)
            self.draft_v_pool = jax.device_put(dv, dsh)

    # -- memory ------------------------------------------------------------
    def kv_pool_bytes(self) -> int:
        leaves = jax.tree.leaves((self.k_pool, self.v_pool))
        return sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in leaves)


def _sample_one_ex(vocab_size: int, n_logprobs: int, logits, hist, sampling,
                   step, mask=None, bias=None):
    """Single-position sampling with optional penalties + logprob report
    (the prefill-first-token path of the decode loop's extras). `hist`
    here is the PROMPT only — nothing has been generated yet, so the
    output-only frequency/presence counts are zero and only repetition
    (prompt+generated semantics) can bite."""
    from dynamo_tpu.engine.sampling import apply_penalties, top_logprobs

    raw = logits[None, :]
    l = raw
    if hist is not None:
        counts = jnp.zeros((1, vocab_size), jnp.float32).at[0, hist].add(
            1.0, mode="drop"
        )
        l = apply_penalties(raw, counts, jnp.zeros_like(counts), sampling)
    s = sample(l, sampling, step, mask=mask, bias=bias)
    if n_logprobs >= 0:
        return (s,) + top_logprobs(raw, s, n_logprobs)
    return (s,)


def _as_sampling(s) -> SamplingParams:
    if isinstance(s, SamplingParams):
        return s
    return SamplingParams.make(
        temperature=s["temperature"], top_k=s["top_k"], top_p=s["top_p"],
        seeds=s["seeds"], rep_penalty=s.get("rep"),
        freq_penalty=s.get("freq"), presence_penalty=s.get("presence"),
    )


def _pad_sampling(s: SamplingParams, B: int) -> SamplingParams:
    n = s.temperature.shape[0]
    if n == B:
        return s
    pad = B - n
    return SamplingParams(
        temperature=jnp.pad(s.temperature, (0, pad)),
        top_k=jnp.pad(s.top_k, (0, pad)),
        top_p=jnp.pad(s.top_p, (0, pad), constant_values=1.0),
        key=jnp.pad(s.key, ((0, pad), (0, 0))),
        rep_penalty=jnp.pad(s.rep_penalty, (0, pad), constant_values=1.0),
        freq_penalty=jnp.pad(s.freq_penalty, (0, pad)),
        presence_penalty=jnp.pad(s.presence_penalty, (0, pad)),
    )
