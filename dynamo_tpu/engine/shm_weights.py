"""Cross-process host-memory weight staging — the TPU answer to the
reference's gpu_memory_service (lib/gpu_memory_service/README.md:1-40).

The reference keeps weights resident in a GPU-memory service so a
restarting worker re-attaches via CUDA IPC handles instead of reloading
from disk. TPUs expose no cross-process device-memory handles, so the
TPU-first equivalent stages the HOST copy in POSIX shared memory
(/dev/shm): the first worker on a host publishes the flattened param
tree once; every peer — SO_REUSEPORT tier members, DP replicas on the
same host, crash-restarted workers — attaches zero-copy read-only numpy
views and device_puts straight out of the mapping. No disk read, no
per-process host duplicate of a multi-GB tree, and the staging survives
the death of the process that created it (segments are detached from
Python's resource tracker exactly so worker crashes don't tear the
cache down).

Commit protocol: ONE segment per stage, written under a per-pid temp
name and os.rename()d into place — atomic on tmpfs, so an attacher can
only ever observe a COMPLETE stage (there is no torn half-published
state to detect or repair, the failure mode heuristic grace periods
exist for). A publisher that dies mid-write leaves only its temp file,
which later publishers garbage-collect by checking the embedded pid is
dead. publish() REPLACES any existing stage (weight-version rollover and
stale-model recovery are both just "publish again"); attachers that
opened the old inode keep their complete mapping until they close it.

Segment layout: [u64 BE index length][msgpack index {version, meta,
entries: [(path, shape, dtype, offset, nbytes)], total}][padding]
[64-byte-aligned array bytes...]. `meta` is caller-owned (the worker
stores a model-config fingerprint and refuses a stage whose fingerprint
disagrees — sharing a stage name across different models is recovered,
not crashed on).

Pairs with the persistent XLA compilation cache (worker
--compilation-cache): together a warm restart skips both recompiles and
weight I/O. Linux-only by construction (tmpfs rename); on hosts without
/dev/shm the tier reports unavailable and workers load cold.
"""

from __future__ import annotations

import logging
import os
import struct
import time
from multiprocessing import shared_memory
from typing import Any, Dict, Optional

import msgpack
import numpy as np

log = logging.getLogger("dynamo_tpu.shm_weights")

VERSION = 2
_ALIGN = 64
_HDR = struct.Struct(">Q")
SHM_DIR = "/dev/shm"


def _seg_name(name: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in name)
    return f"dynshm_{safe}"


def available() -> bool:
    return os.path.isdir(SHM_DIR)


def _keep_after_exit(shm: shared_memory.SharedMemory) -> None:
    """Detach the segment from the resource tracker: staging must outlive
    the creating worker (the whole point — a crashed worker's successor
    attaches instead of reloading). Cleanup is explicit via unlink() or
    replacement by a later publish."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        # tracker internals shifted — staging still works, it just dies
        # with the creator on this Python
        log.debug("resource_tracker unregister failed for %s", shm._name,
                  exc_info=True)


def _flatten(params: Any):
    import jax

    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        leaves.append((key, np.asarray(leaf)))
    return leaves


def _unflatten(entries: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for key, arr in entries.items():
        node = tree
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def _gc_temp_segments(seg: str) -> None:
    """Remove temp files abandoned by dead publishers (name carries the
    writer's pid; a live writer's temp is never touched)."""
    prefix = f"{seg}.p"
    try:
        names = os.listdir(SHM_DIR)
    except OSError:
        return
    for n in names:
        if not n.startswith(prefix):
            continue
        try:
            pid = int(n[len(prefix):])
        except ValueError:
            continue
        if not os.path.exists(f"/proc/{pid}"):
            try:
                os.unlink(os.path.join(SHM_DIR, n))
                log.info("collected abandoned shm temp %s (pid %d dead)",
                         n, pid)
            except OSError:
                pass


def publish(name: str, params: Any, meta: Optional[Dict[str, Any]] = None) -> bool:
    """Stage `params` (pytree of host arrays) under `name`, REPLACING any
    existing stage atomically (rename commit). Returns False only when
    shared memory is unavailable on this host."""
    if not available():
        log.warning("%s missing: shm weight staging disabled", SHM_DIR)
        return False
    # N co-hosted workers cold-booting concurrently would otherwise each
    # write a full temp copy into tmpfs (transient N x multi-GB): when a
    # fingerprinted stage equal to ours already exists, staging is done —
    # skip the copy entirely
    if meta:
        existing = attach(name)
        if existing is not None:
            same = existing.meta == meta
            existing.close()
            if same:
                return False
    seg = _seg_name(name)
    _gc_temp_segments(seg)
    leaves = _flatten(params)
    entries = []
    blob_guess = msgpack.packb(
        {"version": VERSION, "meta": meta or {}, "total": 0,
         "entries": [(k, list(a.shape), str(a.dtype), 0, a.nbytes)
                     for k, a in leaves]},
        use_bin_type=True,
    )
    # data starts after header+index, aligned; offsets are absolute.
    # The guess packed every offset and total as 0 (1 msgpack byte);
    # the real values re-pack into at most 9 bytes each — reserve that
    # growth for ONE offset per leaf plus the total field.
    base = (_HDR.size + len(blob_guess) + 9 * (len(leaves) + 1)
            + _ALIGN - 1) // _ALIGN * _ALIGN
    off = base
    for key, arr in leaves:
        off = (off + _ALIGN - 1) // _ALIGN * _ALIGN
        entries.append((key, list(arr.shape), str(arr.dtype), off, arr.nbytes))
        off += arr.nbytes
    total = max(off, _HDR.size + 1)
    blob = msgpack.packb(
        {"version": VERSION, "meta": meta or {}, "total": total,
         "entries": entries},
        use_bin_type=True,
    )
    assert _HDR.size + len(blob) <= base, "index overran reserved space"

    tmp = f"{seg}.p{os.getpid()}"
    try:
        shm = shared_memory.SharedMemory(name=tmp, create=True, size=total)
    except FileExistsError:
        # our own pid's leftover from a previous interrupted publish
        os.unlink(os.path.join(SHM_DIR, tmp))
        shm = shared_memory.SharedMemory(name=tmp, create=True, size=total)
    try:
        _keep_after_exit(shm)
        shm.buf[: _HDR.size] = _HDR.pack(len(blob))
        shm.buf[_HDR.size : _HDR.size + len(blob)] = blob
        for (key, arr), (_, _, _, o, nb) in zip(leaves, entries):
            dst = np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf,
                             offset=o)
            dst[...] = arr
        # the commit: atomic on tmpfs — attachers only ever see either
        # the previous complete stage or this complete one
        os.rename(os.path.join(SHM_DIR, tmp), os.path.join(SHM_DIR, seg))
        log.info("staged %d arrays (%.1f MB) in shm as %s",
                 len(entries), total / 1e6, name)
        return True
    except BaseException:
        try:
            os.unlink(os.path.join(SHM_DIR, tmp))
        except OSError:
            pass
        raise
    finally:
        shm.close()


class Stage:
    """An attached stage: `params` is a pytree of zero-copy READ-ONLY
    numpy views into shared memory; `meta` is the publisher's fingerprint
    dict. Keep this object alive while the views are in use (it pins the
    mapping — even across a replacing publish, which swaps the name to a
    new inode without disturbing this one)."""

    def __init__(self, shm: shared_memory.SharedMemory, params: Any,
                 meta: Dict[str, Any], n_arrays: int, nbytes: int):
        self._shm = shm
        self.params = params
        self.meta = meta
        self.n_arrays = n_arrays
        self.nbytes = nbytes

    def close(self) -> None:
        self.params = None
        self._shm.close()


def attach(name: str, wait_s: float = 0.0) -> Optional[Stage]:
    """Attach to a published stage; None when absent or unparseable
    (a corrupt segment — e.g. hand-created bytes under our name — is
    logged and treated as absent; the next publish replaces it)."""
    if not available():
        return None
    seg = _seg_name(name)
    deadline = time.monotonic() + wait_s
    while True:
        try:
            shm = shared_memory.SharedMemory(name=seg)
            # CPython < 3.13 registers ATTACH-side handles with the
            # resource tracker too, which unlinks "leaked" segments at
            # interpreter exit — i.e. the first attacher to exit would
            # destroy the stage for every other worker. Detach it.
            _keep_after_exit(shm)
            break
        except FileNotFoundError:
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.1)
    try:
        (blob_len,) = _HDR.unpack(bytes(shm.buf[: _HDR.size]))
        meta = msgpack.unpackb(
            bytes(shm.buf[_HDR.size : _HDR.size + blob_len]), raw=False
        )
        if not isinstance(meta, dict) or meta.get("version") != VERSION:
            raise ValueError(f"version {meta.get('version')!r}"
                             if isinstance(meta, dict) else "not a map")
        import ml_dtypes

        entries: Dict[str, np.ndarray] = {}
        for key, shape, dtype, off, _nb in meta["entries"]:
            dt = (np.dtype(ml_dtypes.bfloat16) if "bfloat16" in dtype
                  else np.dtype(dtype))
            arr = np.ndarray(tuple(shape), dtype=dt, buffer=shm.buf,
                             offset=off)
            # the mapping is shared by every co-hosted worker: an
            # in-place write would corrupt the weights for all of them —
            # make that an immediate local ValueError
            arr.flags.writeable = False
            entries[key] = arr
    except Exception as e:
        log.warning("shm stage %s unreadable (%s); treating as absent",
                    name, e)
        shm.close()
        return None
    return Stage(shm, _unflatten(entries), meta.get("meta") or {},
                 len(entries), meta["total"])


def unlink(name: str) -> None:
    """Explicitly remove a stage (shutdown cleanup; weight rollover needs
    no unlink — publish replaces atomically)."""
    try:
        os.unlink(os.path.join(SHM_DIR, _seg_name(name)))
    except OSError:
        pass
    _gc_temp_segments(_seg_name(name))
