"""Cross-process host-memory weight staging — the TPU answer to the
reference's gpu_memory_service (lib/gpu_memory_service/README.md:1-40).

The reference keeps weights resident in a GPU-memory service so a
restarting worker re-attaches via CUDA IPC handles instead of reloading
from disk. TPUs expose no cross-process device-memory handles, so the
TPU-first equivalent stages the HOST copy in POSIX shared memory
(/dev/shm): the first worker on a host publishes the flattened param
tree once; every peer — SO_REUSEPORT tier members, DP replicas on the
same host, crash-restarted workers — attaches zero-copy numpy views and
device_puts straight out of the mapping. No disk read, no per-process
host duplicate of a multi-GB tree, and the staging survives the death of
the process that created it (we detach the segments from Python's
resource tracker exactly so worker crashes don't tear the cache down).

Layout: two segments per stage name —
  dynshm_<name>_idx   msgpack index {version, entries: [(path, shape,
                      dtype, offset, nbytes)], total}
  dynshm_<name>_data  the concatenated array bytes (64-byte aligned)
The index is created LAST, so attachers treat its existence as the
commit mark; concurrent cold boots race on data creation and the losers
wait for the index.

Pairs with the persistent XLA compilation cache (worker --compilation-
cache): together a warm restart skips both recompiles and weight I/O.
"""

from __future__ import annotations

import logging
import time
from multiprocessing import shared_memory
from typing import Any, Dict, Optional, Tuple

import msgpack
import numpy as np

log = logging.getLogger("dynamo_tpu.shm_weights")

VERSION = 1
_ALIGN = 64


def _seg_names(name: str) -> Tuple[str, str]:
    safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in name)
    return f"dynshm_{safe}_idx", f"dynshm_{safe}_data"


def _keep_after_exit(shm: shared_memory.SharedMemory) -> None:
    """Detach the segment from the resource tracker: staging must outlive
    the creating worker (the whole point — a crashed worker's successor
    attaches instead of reloading). Cleanup is explicit via unlink()."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # tracker internals shifted — staging still works,
        pass  # it just dies with the creator on this Python


def _flatten(params: Any):
    import jax

    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        leaves.append((key, np.asarray(leaf)))
    return leaves


def _unflatten(entries: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for key, arr in entries.items():
        node = tree
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def publish(name: str, params: Any, orphan_grace_s: float = 60.0) -> bool:
    """Stage `params` (pytree of host arrays) under `name`. Returns True
    when this process created the stage, False when another process beat
    us to it (its copy is used). Never raises on a lost race.

    Orphan repair: a publisher killed between creating the data segment
    and committing the index would otherwise brick the name forever
    (publish loses the create race, attach never finds an index). On a
    create collision we wait up to `orphan_grace_s` for the racer's index
    to appear; if it never does, the segment is an orphan — unlink and
    retry the create once."""
    idx_name, data_name = _seg_names(name)
    leaves = _flatten(params)
    entries = []
    off = 0
    for key, arr in leaves:
        off = (off + _ALIGN - 1) // _ALIGN * _ALIGN
        entries.append((key, list(arr.shape), str(arr.dtype), off, arr.nbytes))
        off += arr.nbytes
    total = max(off, 1)
    data = None
    try:
        data = shared_memory.SharedMemory(name=data_name, create=True,
                                          size=total)
    except FileExistsError:
        stage = attach(name, wait_s=orphan_grace_s)
        if stage is not None:
            stage.close()
            return False  # healthy racer staged it
        log.warning(
            "shm stage %s: data segment with no index after %.0fs — "
            "repairing an orphaned publish", name, orphan_grace_s,
        )
        unlink(name)
        try:
            data = shared_memory.SharedMemory(name=data_name, create=True,
                                              size=total)
        except FileExistsError:
            return False  # a racer re-created it concurrently
    try:
        _keep_after_exit(data)
        for (key, arr), (_, _, _, o, nb) in zip(leaves, entries):
            dst = np.ndarray(arr.shape, dtype=arr.dtype, buffer=data.buf,
                             offset=o)
            dst[...] = arr
        blob = msgpack.packb(
            {"version": VERSION, "total": total, "entries": entries},
            use_bin_type=True,
        )
        idx = shared_memory.SharedMemory(name=idx_name, create=True,
                                         size=len(blob))
        _keep_after_exit(idx)
        idx.buf[: len(blob)] = blob
        idx.close()
        log.info("staged %d arrays (%.1f MB) in shm as %s",
                 len(entries), total / 1e6, name)
        return True
    finally:
        data.close()


class Stage:
    """An attached stage: `params` is a pytree of zero-copy numpy views
    into shared memory. Keep this object alive as long as the views are
    in use (it pins the mapping)."""

    def __init__(self, shm: shared_memory.SharedMemory, params: Any,
                 n_arrays: int, nbytes: int):
        self._shm = shm
        self.params = params
        self.n_arrays = n_arrays
        self.nbytes = nbytes

    def close(self) -> None:
        self.params = None
        self._shm.close()


def attach(name: str, wait_s: float = 0.0) -> Optional[Stage]:
    """Attach to a published stage; None when absent. `wait_s` > 0 polls
    for a stage a racing publisher is still writing (its index appears
    only once the data is complete)."""
    idx_name, data_name = _seg_names(name)
    deadline = time.monotonic() + wait_s
    while True:
        try:
            idx = shared_memory.SharedMemory(name=idx_name)
            break
        except FileNotFoundError:
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.1)
    try:
        meta = msgpack.unpackb(bytes(idx.buf), raw=False)
    finally:
        idx.close()
    if meta.get("version") != VERSION:
        log.warning("shm stage %s has version %s != %s; ignoring",
                    name, meta.get("version"), VERSION)
        return None
    try:
        data = shared_memory.SharedMemory(name=data_name)
    except FileNotFoundError:
        # unlink() raced between our idx open and here — stage is gone,
        # which contractually means "absent", never an exception
        return None
    import ml_dtypes

    entries: Dict[str, np.ndarray] = {}
    for key, shape, dtype, off, _nb in meta["entries"]:
        dt = (np.dtype(ml_dtypes.bfloat16) if "bfloat16" in dtype
              else np.dtype(dtype))
        arr = np.ndarray(tuple(shape), dtype=dt, buffer=data.buf, offset=off)
        # the mapping is shared by every co-hosted worker: an in-place
        # write would corrupt the weights for all of them and for every
        # future restart — make that an immediate local ValueError
        arr.flags.writeable = False
        entries[key] = arr
    return Stage(data, _unflatten(entries), len(entries), meta["total"])


def unlink(name: str) -> None:
    """Explicitly remove a stage (weight-version invalidation — the RL
    hot-swap path unlinks before publishing new weights)."""
    for seg in _seg_names(name):
        try:
            shm = shared_memory.SharedMemory(name=seg)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
