"""Model fetch: resolve a checkpoint reference to a local directory.

Analog of the reference's model-hub path (lib/llm/src/hub.rs:728
`fetch_model`: HF-Hub + ModelExpress download before engine boot). Every
entrypoint that takes --checkpoint accepts:

- a local directory (returned as-is),
- `hf://org/name` or a bare `org/name` repo id → downloaded into the
  model cache via huggingface_hub (safetensors + config + tokenizer only
  — no torch .bin duplicates),

with DYN_MODEL_CACHE (default ~/.cache/dynamo_tpu/models) as the cache
root. Offline clusters keep working: a previously-downloaded snapshot is
served from cache (HF_HUB_OFFLINE=1 semantics), and a cache miss with no
egress fails with an actionable error instead of a hang.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Optional

log = logging.getLogger("dynamo_tpu.hub")

_REPO_ID = re.compile(r"^[\w.-]+/[\w.-]+$")

# weights + metadata the engine loader reads; excludes .bin/.pt duplicates
ALLOW_PATTERNS = [
    "*.safetensors", "*.safetensors.index.json", "config.json",
    "generation_config.json", "tokenizer.json", "tokenizer_config.json",
    "special_tokens_map.json", "*.model",
]


def default_cache_dir() -> str:
    return os.environ.get(
        "DYN_MODEL_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "dynamo_tpu", "models"),
    )


def is_repo_id(source: str) -> bool:
    """True for `hf://org/name` or a bare `org/name` that is not a local
    path (an existing directory always wins — never surprise-download
    when the user pointed at files on disk)."""
    if source.startswith("hf://"):
        return True
    return bool(_REPO_ID.match(source)) and not os.path.isdir(source)


def fetch_model(
    source: str, cache_dir: Optional[str] = None, config_only: bool = False
) -> str:
    """Resolve `source` to a local checkpoint dir, downloading from the
    HF Hub when it names a repo id. `config_only` fetches just the
    metadata files (a warm-snapshot restart derives the model config from
    config.json but loads weights from the orbax snapshot — multi-GB
    safetensors must not be re-pulled for that). Raises FileNotFoundError
    for a missing local path and RuntimeError with remediation steps when
    the hub is unreachable and nothing is cached."""
    if os.path.isdir(source):
        return source
    if not is_repo_id(source):
        raise FileNotFoundError(
            f"checkpoint {source!r} is neither a local directory nor an "
            "HF repo id (org/name or hf://org/name)"
        )
    repo = source[5:] if source.startswith("hf://") else source
    cache = cache_dir or default_cache_dir()
    os.makedirs(cache, exist_ok=True)
    patterns = (
        [p for p in ALLOW_PATTERNS if "safetensors" not in p]
        if config_only else ALLOW_PATTERNS
    )
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            f"checkpoint {source!r} needs huggingface_hub to download; "
            "install it or pre-stage the files and pass the local dir"
        ) from e
    try:
        path = snapshot_download(
            repo_id=repo, cache_dir=cache, allow_patterns=patterns
        )
    except Exception:
        # no egress / auth failure: one more chance from local cache only
        try:
            path = snapshot_download(
                repo_id=repo, cache_dir=cache,
                allow_patterns=patterns, local_files_only=True,
            )
            log.info("hub unreachable; serving %s from cache", repo)
        except Exception as e:
            raise RuntimeError(
                f"cannot fetch {repo!r}: hub unreachable and not cached "
                f"under {cache}. Pre-stage with `huggingface-cli download "
                f"{repo}` on a connected host, or pass a local checkpoint "
                "dir."
            ) from e
    log.info("checkpoint %s resolved to %s", source, path)
    return path
