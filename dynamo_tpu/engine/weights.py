"""Checkpoint loading: HF safetensors / orbax → the engine's param tree.

Fills the role of the reference's model-fetch path (lib/llm/src/hub.rs +
per-backend weight loading inside vLLM/TRT-LLM): map a HuggingFace
Llama-family checkpoint directory onto models/llama.py's stacked-layer
pytree, casting to the serving dtype, ready for ShardingPolicy placement.

HF → dynamo_tpu name map (Llama/Mistral/Qwen2/Qwen3/Qwen-MoE/OLMo-2
architectures; Phi-3's fused qkv_proj/gate_up_proj resolve to the split
names below via virtual get_slice row-splits, Mixtral's
block_sparse_moe.experts.N.{w1,w3,w2} map to we_{gate,up,down}, and
Gemma-1/2/3 / DeepSeek-MLA deviations are noted inline):
  model.embed_tokens.weight            → embed                [V, E]
  model.layers.{i}.input_layernorm     → layers/attn_norm[i]
  model.layers.{i}.self_attn.{q,k,v}_proj (transposed) → layers/w{q,k,v}[i]
  model.layers.{i}.self_attn.{q,k,v}_proj.bias → layers/b{q,k,v}[i] (Qwen2)
  model.layers.{i}.self_attn.{q,k}_norm.weight → layers/{q,k}_norm[i] (Qwen3)
  model.layers.{i}.self_attn.o_proj    (transposed)    → layers/wo[i]
  model.layers.{i}.post_attention_layernorm → layers/mlp_norm[i]
  model.layers.{i}.mlp.{gate,up,down}_proj (transposed) → layers/w_{gate,up,down}[i]
  model.layers.{i}.mlp.gate.weight (transposed)        → layers/w_router[i] (MoE)
  model.layers.{i}.mlp.experts.{e}.{gate,up,down}_proj → layers/we_*[i, e]
  model.layers.{i}.mlp.shared_expert.{gate,up,down}_proj → layers/ws_*[i]
    (DeepSeek naming `shared_experts` accepted too)
  model.norm.weight                    → norm_f
  lm_head.weight (transposed)          → lm_head (absent if tied)
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any, Dict, Optional

import numpy as np

from dynamo_tpu.models.config import ModelConfig

log = logging.getLogger("dynamo_tpu.engine.weights")


def load_hf_checkpoint(
    checkpoint_dir: str, config: ModelConfig, dtype="bfloat16"
) -> Dict[str, Any]:
    """Load a HF Llama safetensors checkpoint into the stacked param tree
    (numpy arrays; the ModelRunner device_puts them with shardings)."""
    import ml_dtypes
    from safetensors import safe_open

    np_dtype = np.dtype(ml_dtypes.bfloat16) if dtype == "bfloat16" else np.dtype(dtype)
    d = Path(checkpoint_dir)
    files = sorted(d.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {checkpoint_dir}")

    # name -> file handle index
    tensors: Dict[str, Any] = {}
    handles = []
    for f in files:
        h = safe_open(str(f), framework="numpy")
        handles.append(h)
        for name in h.keys():
            # multimodal wrappers (Gemma-3 vision+text) prefix the LM
            # tree with "language_model."; alias the stripped name so the
            # text mapping below serves both checkpoint shapes (the value
            # keeps the REAL key the file must be read with)
            if name.startswith("language_model."):
                tensors[name[len("language_model."):]] = (h, name)
            tensors[name] = (h, name)

    def _raw(name: str) -> np.ndarray:
        if name in tensors:
            h, key = tensors[name]
            return h.get_tensor(key)
        # Phi-3 fuses q/k/v into qkv_proj and gate/up into gate_up_proj
        # (rows [q; k; v] resp. [gate; up] in the HF [out, in] layout).
        # Resolve the split names virtually so one mapping serves both
        # checkpoint shapes.
        parts = name.split(".")
        proj = parts[-2] if len(parts) >= 2 else ""
        if proj in ("q_proj", "k_proj", "v_proj"):
            fused = ".".join(parts[:-2] + ["qkv_proj", parts[-1]])
            if fused in tensors:
                h, key = tensors[fused]
                q = config.n_heads * config.head_dim
                kv = config.n_kv_heads * config.head_dim
                lo = {"q_proj": 0, "k_proj": q, "v_proj": q + kv}[proj]
                # get_slice reads only the needed rows (q is read 3x per
                # layer otherwise — gigabytes of redundant IO at 7B scale)
                return h.get_slice(key)[lo:lo + (q if proj == "q_proj" else kv)]
        if proj in ("gate_proj", "up_proj"):
            fused = ".".join(parts[:-2] + ["gate_up_proj", parts[-1]])
            if fused in tensors:
                h, key = tensors[fused]
                f = config.ffn_dim
                sl = h.get_slice(key)
                return sl[:f] if proj == "gate_proj" else sl[f:2 * f]
        raise KeyError(name)

    def get(name: str, transpose: bool = False) -> np.ndarray:
        arr = _raw(name)
        if transpose:
            arr = arr.T
        return np.ascontiguousarray(arr).astype(np_dtype)

    def get_f32(name: str) -> np.ndarray:
        return _raw(name).astype(np.float32)

    L = config.n_layers
    if config.is_mla:
        return _load_mla(config, tensors, get, get_f32, checkpoint_dir)
    first_q = get("model.layers.0.self_attn.q_proj.weight", transpose=True)
    if first_q.shape != (config.dim, config.n_heads * config.head_dim):
        raise ValueError(
            f"checkpoint shape {first_q.shape} does not match config "
            f"{config.name} ({config.dim}, {config.n_heads * config.head_dim})"
        )

    def stack(fmt: str, transpose: bool) -> np.ndarray:
        return np.stack([get(fmt.format(i=i), transpose=transpose) for i in range(L)])

    def stack_f32(fmt: str) -> np.ndarray:
        return np.stack([get_f32(fmt.format(i=i)) for i in range(L)])

    # Gemma-2 renames: post_attention_layernorm is the POST-attn sandwich
    # norm (not the pre-FFW norm llama uses it for); the pre-FFW norm is
    # pre_feedforward_layernorm
    mlp_norm_name = (
        "model.layers.{i}.pre_feedforward_layernorm.weight"
        if config.post_norms
        else "model.layers.{i}.post_attention_layernorm.weight"
    )
    params: Dict[str, Any] = {
        "embed": get("model.embed_tokens.weight"),
        "layers": {
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight", True),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight", True),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight", True),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight", True),
        },
        "norm_f": get_f32("model.norm.weight"),
    }
    layers = params["layers"]
    if config.pre_norms:
        layers["attn_norm"] = stack_f32(
            "model.layers.{i}.input_layernorm.weight"
        )
        layers["mlp_norm"] = stack_f32(mlp_norm_name)
    if config.post_norms:
        layers["post_attn_norm"] = stack_f32(
            "model.layers.{i}.post_attention_layernorm.weight"
        )
        layers["post_mlp_norm"] = stack_f32(
            "model.layers.{i}.post_feedforward_layernorm.weight"
        )
    if config.attn_bias:
        layers["bq"] = stack("model.layers.{i}.self_attn.q_proj.bias", False)
        layers["bk"] = stack("model.layers.{i}.self_attn.k_proj.bias", False)
        layers["bv"] = stack("model.layers.{i}.self_attn.v_proj.bias", False)
    if config.qk_norm:
        layers["q_norm"] = stack_f32("model.layers.{i}.self_attn.q_norm.weight")
        layers["k_norm"] = stack_f32("model.layers.{i}.self_attn.k_norm.weight")
    if config.is_moe:
        # two MoE tensor layouts in the wild: qwen/deepseek
        # (mlp.gate + mlp.experts.N.{gate,up,down}_proj) and Mixtral
        # (block_sparse_moe.gate + experts.N.{w1,w3,w2} where w1=gate,
        # w3=up, w2=down)
        mixtral = (
            "model.layers.0.block_sparse_moe.gate.weight" in tensors
        )
        moe_base = "block_sparse_moe" if mixtral else "mlp"
        part_names = (
            {"gate_proj": "w1", "up_proj": "w3", "down_proj": "w2"}
            if mixtral else
            {"gate_proj": "gate_proj", "up_proj": "up_proj",
             "down_proj": "down_proj"}
        )
        layers["w_router"] = stack(
            "model.layers.{i}." + moe_base + ".gate.weight", True
        )

        def stack_experts(part: str) -> np.ndarray:
            p = part_names[part]
            return np.stack(
                [
                    np.stack(
                        [
                            get(
                                f"model.layers.{i}.{moe_base}.experts.{e}.{p}.weight",
                                transpose=True,
                            )
                            for e in range(config.n_experts)
                        ]
                    )
                    for i in range(L)
                ]
            )

        layers["we_gate"] = stack_experts("gate_proj")
        layers["we_up"] = stack_experts("up_proj")
        layers["we_down"] = stack_experts("down_proj")
        if config.n_shared_experts:
            base = "model.layers.{i}.mlp.shared_expert"
            if f"model.layers.0.mlp.shared_experts.gate_proj.weight" in tensors:
                base = "model.layers.{i}.mlp.shared_experts"  # deepseek naming
            layers["ws_gate"] = stack(base + ".gate_proj.weight", True)
            layers["ws_up"] = stack(base + ".up_proj.weight", True)
            layers["ws_down"] = stack(base + ".down_proj.weight", True)
            if "model.layers.0.mlp.shared_expert_gate.weight" in tensors:
                layers["ws_gatectl"] = stack(
                    "model.layers.{i}.mlp.shared_expert_gate.weight", True
                )
    else:
        layers["w_gate"] = stack("model.layers.{i}.mlp.gate_proj.weight", True)
        layers["w_up"] = stack("model.layers.{i}.mlp.up_proj.weight", True)
        layers["w_down"] = stack("model.layers.{i}.mlp.down_proj.weight", True)
    if "lm_head.weight" in tensors and not config.tie_embeddings:
        params["lm_head"] = get("lm_head.weight", transpose=True)
    log.info("loaded HF checkpoint %s (%d files)", checkpoint_dir, len(files))
    return params


def _rope_deinterleave(d: int) -> np.ndarray:
    """Column permutation converting HF DeepSeek's INTERLEAVED rope layout
    (x0,y0,x1,y1,...) to this module's half-rotation layout (all x then
    all y). The HF modeling file performs this view-transpose at runtime
    on q_pe/k_pe every step; folding it into the weights once at load
    makes the layouts agree with models/llama.py's rope()."""
    return np.concatenate([np.arange(0, d, 2), np.arange(1, d, 2)])


def _load_mla(config: ModelConfig, tensors, get, get_f32,
              checkpoint_dir: str) -> Dict[str, Any]:
    """DeepSeek V2/V3 MLA checkpoint → the stacked (layers_dense, layers)
    trees. HF names: kv_a_proj_with_mqa / kv_a_layernorm / kv_b_proj,
    q_proj or q_a_proj/q_a_layernorm/q_b_proj, o_proj; MoE layers carry
    mlp.experts.{e}.* + mlp.shared_experts.* + mlp.gate.weight (+
    e_score_correction_bias)."""
    c = config
    L, kD = c.n_layers, c.n_dense_layers
    dn, dr, dv, dc = (c.qk_nope_head_dim, c.qk_rope_head_dim,
                      c.v_head_dim, c.kv_lora_rank)
    rp = _rope_deinterleave(dr)

    def attn_rows(i: int) -> Dict[str, Any]:
        pre = f"model.layers.{i}."
        wkv_a = get(pre + "self_attn.kv_a_proj_with_mqa.weight", True)
        # de-interleave the k_pe block (last dr output columns)
        wkv_a[:, dc:] = wkv_a[:, dc:][:, rp]
        row = {
            "attn_norm": get_f32(pre + "input_layernorm.weight"),
            "wkv_a": wkv_a,
            "kv_norm": get_f32(pre + "self_attn.kv_a_layernorm.weight"),
            "wkv_b": get(pre + "self_attn.kv_b_proj.weight", True),
            "wo": get(pre + "self_attn.o_proj.weight", True),
            "mlp_norm": get_f32(pre + "post_attention_layernorm.weight"),
        }

        def fix_q(wq: np.ndarray) -> np.ndarray:
            # per head, de-interleave the rope block [dn:dn+dr]
            w3 = wq.reshape(wq.shape[0], c.n_heads, dn + dr)
            w3[:, :, dn:] = w3[:, :, dn:][:, :, rp]
            return w3.reshape(wq.shape)

        if c.q_lora_rank:
            row["wq_lat"] = get(pre + "self_attn.q_a_proj.weight", True)
            row["q_lat_norm"] = get_f32(pre + "self_attn.q_a_layernorm.weight")
            row["wq_up"] = fix_q(get(pre + "self_attn.q_b_proj.weight", True))
        else:
            row["wq"] = fix_q(get(pre + "self_attn.q_proj.weight", True))
        return row

    def dense_rows(i: int) -> Dict[str, Any]:
        pre = f"model.layers.{i}.mlp."
        return {
            "w_gate": get(pre + "gate_proj.weight", True),
            "w_up": get(pre + "up_proj.weight", True),
            "w_down": get(pre + "down_proj.weight", True),
        }

    def moe_rows(i: int) -> Dict[str, Any]:
        pre = f"model.layers.{i}.mlp."
        row = {
            "w_router": get(pre + "gate.weight", True),
            "we_gate": np.stack([
                get(f"{pre}experts.{e}.gate_proj.weight", True)
                for e in range(c.n_experts)
            ]),
            "we_up": np.stack([
                get(f"{pre}experts.{e}.up_proj.weight", True)
                for e in range(c.n_experts)
            ]),
            "we_down": np.stack([
                get(f"{pre}experts.{e}.down_proj.weight", True)
                for e in range(c.n_experts)
            ]),
        }
        if c.moe_router_bias:
            row["router_bias"] = get_f32(pre + "gate.e_score_correction_bias")
        if c.n_shared_experts:
            row["ws_gate"] = get(pre + "shared_experts.gate_proj.weight", True)
            row["ws_up"] = get(pre + "shared_experts.up_proj.weight", True)
            row["ws_down"] = get(pre + "shared_experts.down_proj.weight", True)
        return row

    def stack_rows(rows: list) -> Dict[str, Any]:
        return {k: np.stack([r[k] for r in rows]) for k in rows[0]}

    moe_layers = [
        {**attn_rows(i), **(moe_rows(i) if c.is_moe else dense_rows(i))}
        for i in range(kD, L)
    ]
    params: Dict[str, Any] = {
        "embed": get("model.embed_tokens.weight"),
        "layers": stack_rows(moe_layers),
        "norm_f": get_f32("model.norm.weight"),
    }
    if kD:
        params["layers_dense"] = stack_rows(
            [{**attn_rows(i), **dense_rows(i)} for i in range(kD)]
        )
    if "lm_head.weight" in tensors and not c.tie_embeddings:
        params["lm_head"] = get("lm_head.weight", True)
    log.info("loaded DeepSeek MLA checkpoint %s", checkpoint_dir)
    return params


def config_from_hf(checkpoint_dir: str, name: Optional[str] = None) -> ModelConfig:
    """Derive a ModelConfig from a HF config.json (llama / qwen2 / qwen3 /
    qwen2_moe / qwen3_moe model types)."""
    cfg = json.loads((Path(checkpoint_dir) / "config.json").read_text())
    mt = cfg.get("model_type", "llama")
    if mt == "gemma3" and isinstance(cfg.get("text_config"), dict):
        # multimodal wrapper config: the LM (incl. its rope_scaling!)
        # lives under text_config — unwrap BEFORE any field is read.
        # HF serializes NESTED configs as diffs against the class
        # defaults, so a real gemma-3-*-it text_config omits defaulted
        # fields (rope_theta 1e6, sliding_window, query_pre_attn_scalar,
        # ...) — overlay the upstream defaults underneath or those fields
        # silently pick up OUR generic fallbacks (wrong logits).
        defaults: Dict[str, Any] = {}
        try:
            import transformers as _tf

            defaults = _tf.Gemma3TextConfig().to_dict()
        except Exception:
            # loader must work without transformers: pin the defaults our
            # mapping reads (upstream Gemma3TextConfig values)
            defaults = {
                "rope_theta": 1_000_000.0, "rope_local_base_freq": 10_000.0,
                "sliding_window": 4096, "query_pre_attn_scalar": 256.0,
                "head_dim": 256, "rms_norm_eps": 1e-6,
                "max_position_embeddings": 131072,
                "tie_word_embeddings": True,
            }
        cfg = {**defaults, **cfg["text_config"], "model_type": "gemma3_text"}
        mt = "gemma3_text"
    rope_kw = _rope_scaling_from_hf(cfg)
    if mt.startswith("deepseek"):
        return ModelConfig(
            **rope_kw,
            n_expert_groups=int(cfg.get("n_group") or 0),
            topk_groups=int(cfg.get("topk_group") or 0),
            name=name or cfg.get("_name_or_path", "deepseek-hf"),
            vocab_size=cfg["vocab_size"],
            dim=cfg["hidden_size"],
            n_layers=cfg["num_hidden_layers"],
            n_heads=cfg["num_attention_heads"],
            n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            ffn_dim=cfg["intermediate_size"],
            max_seq_len=cfg.get("max_position_embeddings", 8192),
            rope_theta=float(cfg.get("rope_theta", 10000.0)),
            norm_eps=float(cfg.get("rms_norm_eps", 1e-6)),
            tie_embeddings=bool(cfg.get("tie_word_embeddings", False)),
            attn_type="mla",
            kv_lora_rank=int(cfg["kv_lora_rank"]),
            q_lora_rank=int(cfg.get("q_lora_rank") or 0),
            qk_rope_head_dim=int(cfg["qk_rope_head_dim"]),
            qk_nope_head_dim=int(cfg["qk_nope_head_dim"]),
            v_head_dim=int(cfg["v_head_dim"]),
            n_experts=int(cfg.get("n_routed_experts") or 0),
            n_experts_active=int(cfg.get("num_experts_per_tok") or 0),
            moe_ffn_dim=int(cfg.get("moe_intermediate_size") or 0),
            n_shared_experts=int(cfg.get("n_shared_experts") or 0),
            moe_scoring=(
                "sigmoid" if cfg.get("scoring_func") == "sigmoid" else "softmax"
            ),
            moe_norm_topk=bool(cfg.get("norm_topk_prob", True)),
            # V3's aux-loss-free balancing ships the correction bias
            moe_router_bias=cfg.get("topk_method") == "noaux_tc",
            moe_routed_scale=float(cfg.get("routed_scaling_factor") or 1.0),
            n_dense_layers=int(cfg.get("first_k_dense_replace") or 0),
        )
    n_experts = int(cfg.get("num_experts") or cfg.get("n_routed_experts")
                    or cfg.get("num_local_experts") or 0)  # mixtral naming
    gemma2 = mt == "gemma2"
    gemma3 = mt.startswith("gemma3")
    gemma_kw = {}
    if mt == "granite":
        # Granite: Llama layout + four scalar multipliers (HF
        # GraniteConfig); logits_scaling DIVIDES the final logits
        gemma_kw.update(
            embed_multiplier=float(cfg.get("embedding_multiplier") or 0.0),
            residual_multiplier=float(cfg.get("residual_multiplier") or 1.0),
            # HF's default when the field is omitted is 1.0 — i.e. a
            # softmax scale of ONE, not head_dim**-0.5
            attn_scale=float(cfg.get("attention_multiplier", 1.0) or 1.0),
            logits_divider=float(cfg.get("logits_scaling") or 1.0),
        )
    if mt == "olmo2":
        # OLMo-2 reorders the norms: NO pre-norms — the residual stream
        # feeds attention/MLP raw and post_{attention,feedforward}_
        # layernorm norm the branch OUTPUTS (same tensor names Gemma-2
        # uses for its sandwich); qk-norm runs over the FULL projection
        # width before the head reshape.
        gemma_kw.update(post_norms=True, pre_norms=False, qk_norm_wide=True)
    if mt == "gemma":
        # Gemma-1: the GeGLU/scaled-embed/zero-centered-norm subset of
        # the Gemma-2 flags — no sandwich norms, softcaps, or window
        gemma_kw.update(
            act="gelu_tanh",
            embed_scale=True,
            norm_zero_centered=True,
        )
    if mt in ("mistral", "mixtral", "phi3") and cfg.get("sliding_window"):
        # Mistral-family sliding window applies to EVERY layer (HF
        # masks q-k >= sliding_window on all of them — no alternation).
        # Expressed in the generalized schedule as period 1 with an
        # unreachable global residue: (l % 1) == 1 is never true.
        gemma_kw.update(
            sliding_window=int(cfg["sliding_window"]),
            sw_period=1,
            sw_global_residue=1,
        )
    if gemma2 or gemma3:
        gemma_kw = dict(
            act="gelu_tanh",
            embed_scale=True,
            norm_zero_centered=True,
            post_norms=True,
            attn_logit_softcap=float(cfg.get("attn_logit_softcapping") or 0.0),
            final_logit_softcap=float(
                cfg.get("final_logit_softcapping") or 0.0
            ),
            query_pre_attn_scalar=float(
                cfg.get("query_pre_attn_scalar") or 0.0
            ),
            sliding_window=int(cfg.get("sliding_window") or 0),
        )
    if gemma3:
        # 5 local : 1 global pattern + dual rope bases. Derive the
        # period/residue from layer_types when present and verify it is
        # the canonical periodic pattern — silently mis-phasing the
        # window schedule would corrupt logits with no error.
        layer_types = cfg.get("layer_types")
        period = int(cfg.get("sliding_window_pattern") or 6)
        if layer_types:
            globals_ = [i for i, t in enumerate(layer_types)
                        if t == "full_attention"]
            if globals_:
                period = globals_[0] + 1
            expect = [
                "full_attention" if (i % period) == period - 1
                else "sliding_attention"
                for i in range(len(layer_types))
            ]
            if layer_types != expect:
                raise ValueError(
                    "gemma3 layer_types is not the canonical "
                    f"{period - 1}:1 local/global pattern; refusing to "
                    "mis-phase the sliding schedule"
                )
        gemma_kw.update(
            sw_period=period,
            sw_global_residue=period - 1,
            # HF's default when the field is omitted is 10000.0; falling
            # back to 0.0 would silently disable the dual rope and rotate
            # sliding layers with the 1e6 global base
            rope_local_theta=float(
                cfg.get("rope_local_base_freq", 10000.0) or 10000.0
            ),
        )
    return ModelConfig(
        **rope_kw,
        **gemma_kw,
        name=name or cfg.get("_name_or_path", "hf-model"),
        vocab_size=cfg["vocab_size"],
        dim=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
        ffn_dim=cfg["intermediate_size"],
        max_seq_len=cfg.get("max_position_embeddings", 8192),
        rope_theta=float(cfg.get("rope_theta", 500000.0)),
        norm_eps=float(cfg.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(cfg.get("tie_word_embeddings", False)),
        # qwen2 ships biases by default; qwen3 advertises them explicitly
        attn_bias=bool(cfg.get("attention_bias", mt in ("qwen2", "qwen2_moe"))),
        qk_norm=mt in ("qwen3", "qwen3_moe", "olmo2") or gemma3,
        head_dim_override=int(cfg.get("head_dim") or 0),
        n_experts=n_experts,
        n_experts_active=int(cfg.get("num_experts_per_tok") or 0),
        # mixtral has no separate moe_intermediate_size: its experts use
        # the dense intermediate width. The fallback is gated on the
        # MODEL TYPE, not n_experts — a qwen-family MoE config that
        # diff-omits moe_intermediate_size must keep failing loudly on
        # wrong shapes, not silently adopt the dense width
        moe_ffn_dim=int(
            cfg.get("moe_intermediate_size")
            or (cfg.get("intermediate_size") if mt == "mixtral" else 0)
            or 0
        ),
        n_shared_experts=int(
            cfg.get("n_shared_experts")
            or (1 if cfg.get("shared_expert_intermediate_size") else 0)
        ),
        shared_expert_ffn_dim=int(cfg.get("shared_expert_intermediate_size") or 0),
        moe_scoring="sigmoid" if cfg.get("scoring_func") == "sigmoid" else "softmax",
        # Qwen2-MoE ships norm_topk_prob=false: keep softmax-over-all
        # probabilities un-renormalized (HF semantics)
        moe_norm_topk=bool(cfg.get("norm_topk_prob", True)),
    )


def _rope_scaling_from_hf(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """HF rope_scaling dict → ModelConfig rope_* kwargs. Unknown scaling
    types raise — silently ignoring one produces numerically wrong
    long-context attention."""
    rs = cfg.get("rope_scaling")
    if not rs:
        return {}
    kind = rs.get("rope_type") or rs.get("type") or ""
    if kind == "llama3":
        return {
            "rope_scaling": "llama3",
            "rope_factor": float(rs.get("factor", 8.0)),
            "rope_orig_max_seq": int(
                rs.get("original_max_position_embeddings") or 8192
            ),
            "rope_low_freq_factor": float(rs.get("low_freq_factor", 1.0)),
            "rope_high_freq_factor": float(rs.get("high_freq_factor", 4.0)),
        }
    if kind in ("linear", "default"):
        # uniform position interpolation (Gemma-3 global rope: factor 8);
        # "default" is HF's explicit no-op
        f = float(rs.get("factor", 1.0))
        if f == 1.0 or kind == "default":
            return {}
        return {"rope_scaling": "linear", "rope_factor": f}
    if kind == "yarn":
        return {
            "rope_scaling": "yarn",
            "rope_factor": float(rs.get("factor", 1.0)),
            "rope_orig_max_seq": int(
                rs.get("original_max_position_embeddings") or 4096
            ),
            "rope_beta_fast": float(rs.get("beta_fast", 32.0)),
            "rope_beta_slow": float(rs.get("beta_slow", 1.0)),
            "rope_mscale": float(rs.get("mscale", 1.0)),
            "rope_mscale_all_dim": float(rs.get("mscale_all_dim", 0.0)),
        }
    raise ValueError(
        f"unsupported rope_scaling type {kind!r} (supported: llama3, yarn)"
    )


def save_orbax(params: Dict[str, Any], path: str) -> None:
    """Persist a param tree with orbax (fast-resume staging; the TPU analog
    of the reference's GMS/ModelExpress fast-restart role)."""
    import orbax.checkpoint as ocp

    ckpt = ocp.StandardCheckpointer()
    ckpt.save(Path(path).resolve(), params, force=True)
    ckpt.wait_until_finished()


def load_orbax(path: str) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    ckpt = ocp.StandardCheckpointer()
    return ckpt.restore(Path(path).resolve())
