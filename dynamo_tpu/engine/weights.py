"""Checkpoint loading: HF safetensors / orbax → the engine's param tree.

Fills the role of the reference's model-fetch path (lib/llm/src/hub.rs +
per-backend weight loading inside vLLM/TRT-LLM): map a HuggingFace
Llama-family checkpoint directory onto models/llama.py's stacked-layer
pytree, casting to the serving dtype, ready for ShardingPolicy placement.

HF → dynamo_tpu name map (Llama/Qwen2/Qwen3/Qwen-MoE architectures):
  model.embed_tokens.weight            → embed                [V, E]
  model.layers.{i}.input_layernorm     → layers/attn_norm[i]
  model.layers.{i}.self_attn.{q,k,v}_proj (transposed) → layers/w{q,k,v}[i]
  model.layers.{i}.self_attn.{q,k,v}_proj.bias → layers/b{q,k,v}[i] (Qwen2)
  model.layers.{i}.self_attn.{q,k}_norm.weight → layers/{q,k}_norm[i] (Qwen3)
  model.layers.{i}.self_attn.o_proj    (transposed)    → layers/wo[i]
  model.layers.{i}.post_attention_layernorm → layers/mlp_norm[i]
  model.layers.{i}.mlp.{gate,up,down}_proj (transposed) → layers/w_{gate,up,down}[i]
  model.layers.{i}.mlp.gate.weight (transposed)        → layers/w_router[i] (MoE)
  model.layers.{i}.mlp.experts.{e}.{gate,up,down}_proj → layers/we_*[i, e]
  model.layers.{i}.mlp.shared_expert.{gate,up,down}_proj → layers/ws_*[i]
    (DeepSeek naming `shared_experts` accepted too)
  model.norm.weight                    → norm_f
  lm_head.weight (transposed)          → lm_head (absent if tied)
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any, Dict, Optional

import numpy as np

from dynamo_tpu.models.config import ModelConfig

log = logging.getLogger("dynamo_tpu.engine.weights")


def load_hf_checkpoint(
    checkpoint_dir: str, config: ModelConfig, dtype="bfloat16"
) -> Dict[str, Any]:
    """Load a HF Llama safetensors checkpoint into the stacked param tree
    (numpy arrays; the ModelRunner device_puts them with shardings)."""
    import ml_dtypes
    from safetensors import safe_open

    np_dtype = np.dtype(ml_dtypes.bfloat16) if dtype == "bfloat16" else np.dtype(dtype)
    d = Path(checkpoint_dir)
    files = sorted(d.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {checkpoint_dir}")

    # name -> file handle index
    tensors: Dict[str, Any] = {}
    handles = []
    for f in files:
        h = safe_open(str(f), framework="numpy")
        handles.append(h)
        for name in h.keys():
            tensors[name] = h

    def get(name: str, transpose: bool = False) -> np.ndarray:
        arr = tensors[name].get_tensor(name)
        if transpose:
            arr = arr.T
        return np.ascontiguousarray(arr).astype(np_dtype)

    def get_f32(name: str) -> np.ndarray:
        return tensors[name].get_tensor(name).astype(np.float32)

    L = config.n_layers
    first_q = get("model.layers.0.self_attn.q_proj.weight", transpose=True)
    if first_q.shape != (config.dim, config.n_heads * config.head_dim):
        raise ValueError(
            f"checkpoint shape {first_q.shape} does not match config "
            f"{config.name} ({config.dim}, {config.n_heads * config.head_dim})"
        )

    def stack(fmt: str, transpose: bool) -> np.ndarray:
        return np.stack([get(fmt.format(i=i), transpose=transpose) for i in range(L)])

    def stack_f32(fmt: str) -> np.ndarray:
        return np.stack([get_f32(fmt.format(i=i)) for i in range(L)])

    params: Dict[str, Any] = {
        "embed": get("model.embed_tokens.weight"),
        "layers": {
            "attn_norm": stack_f32("model.layers.{i}.input_layernorm.weight"),
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight", True),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight", True),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight", True),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight", True),
            "mlp_norm": stack_f32("model.layers.{i}.post_attention_layernorm.weight"),
        },
        "norm_f": get_f32("model.norm.weight"),
    }
    layers = params["layers"]
    if config.attn_bias:
        layers["bq"] = stack("model.layers.{i}.self_attn.q_proj.bias", False)
        layers["bk"] = stack("model.layers.{i}.self_attn.k_proj.bias", False)
        layers["bv"] = stack("model.layers.{i}.self_attn.v_proj.bias", False)
    if config.qk_norm:
        layers["q_norm"] = stack_f32("model.layers.{i}.self_attn.q_norm.weight")
        layers["k_norm"] = stack_f32("model.layers.{i}.self_attn.k_norm.weight")
    if config.is_moe:
        layers["w_router"] = stack("model.layers.{i}.mlp.gate.weight", True)

        def stack_experts(part: str) -> np.ndarray:
            return np.stack(
                [
                    np.stack(
                        [
                            get(
                                f"model.layers.{i}.mlp.experts.{e}.{part}.weight",
                                transpose=True,
                            )
                            for e in range(config.n_experts)
                        ]
                    )
                    for i in range(L)
                ]
            )

        layers["we_gate"] = stack_experts("gate_proj")
        layers["we_up"] = stack_experts("up_proj")
        layers["we_down"] = stack_experts("down_proj")
        if config.n_shared_experts:
            base = "model.layers.{i}.mlp.shared_expert"
            if f"model.layers.0.mlp.shared_experts.gate_proj.weight" in tensors:
                base = "model.layers.{i}.mlp.shared_experts"  # deepseek naming
            layers["ws_gate"] = stack(base + ".gate_proj.weight", True)
            layers["ws_up"] = stack(base + ".up_proj.weight", True)
            layers["ws_down"] = stack(base + ".down_proj.weight", True)
            if "model.layers.0.mlp.shared_expert_gate.weight" in tensors:
                layers["ws_gatectl"] = stack(
                    "model.layers.{i}.mlp.shared_expert_gate.weight", True
                )
    else:
        layers["w_gate"] = stack("model.layers.{i}.mlp.gate_proj.weight", True)
        layers["w_up"] = stack("model.layers.{i}.mlp.up_proj.weight", True)
        layers["w_down"] = stack("model.layers.{i}.mlp.down_proj.weight", True)
    if "lm_head.weight" in tensors and not config.tie_embeddings:
        params["lm_head"] = get("lm_head.weight", transpose=True)
    log.info("loaded HF checkpoint %s (%d files)", checkpoint_dir, len(files))
    return params


def config_from_hf(checkpoint_dir: str, name: Optional[str] = None) -> ModelConfig:
    """Derive a ModelConfig from a HF config.json (llama / qwen2 / qwen3 /
    qwen2_moe / qwen3_moe model types)."""
    cfg = json.loads((Path(checkpoint_dir) / "config.json").read_text())
    mt = cfg.get("model_type", "llama")
    if mt.startswith("deepseek"):
        # DeepSeek checkpoints need MLA attention, leading dense layers
        # (first_k_dense_replace) and bias-corrected sigmoid routing with
        # routed_scaling_factor — none of which this loader maps yet.
        # Refusing beats silently mis-mapping a 600B checkpoint.
        raise ValueError(
            f"model_type {mt!r} (MLA) is not supported by this loader; "
            "supported: llama, qwen2, qwen3, qwen2_moe, qwen3_moe"
        )
    n_experts = int(cfg.get("num_experts") or cfg.get("n_routed_experts") or 0)
    return ModelConfig(
        name=name or cfg.get("_name_or_path", "hf-model"),
        vocab_size=cfg["vocab_size"],
        dim=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
        ffn_dim=cfg["intermediate_size"],
        max_seq_len=cfg.get("max_position_embeddings", 8192),
        rope_theta=float(cfg.get("rope_theta", 500000.0)),
        norm_eps=float(cfg.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(cfg.get("tie_word_embeddings", False)),
        # qwen2 ships biases by default; qwen3 advertises them explicitly
        attn_bias=bool(cfg.get("attention_bias", mt in ("qwen2", "qwen2_moe"))),
        qk_norm=mt in ("qwen3", "qwen3_moe"),
        head_dim_override=int(cfg.get("head_dim") or 0),
        n_experts=n_experts,
        n_experts_active=int(cfg.get("num_experts_per_tok") or 0),
        moe_ffn_dim=int(cfg.get("moe_intermediate_size") or 0),
        n_shared_experts=int(
            cfg.get("n_shared_experts")
            or (1 if cfg.get("shared_expert_intermediate_size") else 0)
        ),
        shared_expert_ffn_dim=int(cfg.get("shared_expert_intermediate_size") or 0),
        moe_scoring="sigmoid" if cfg.get("scoring_func") == "sigmoid" else "softmax",
        # Qwen2-MoE ships norm_topk_prob=false: keep softmax-over-all
        # probabilities un-renormalized (HF semantics)
        moe_norm_topk=bool(cfg.get("norm_topk_prob", True)),
    )


def save_orbax(params: Dict[str, Any], path: str) -> None:
    """Persist a param tree with orbax (fast-resume staging; the TPU analog
    of the reference's GMS/ModelExpress fast-restart role)."""
    import orbax.checkpoint as ocp

    ckpt = ocp.StandardCheckpointer()
    ckpt.save(Path(path).resolve(), params, force=True)
    ckpt.wait_until_finished()


def load_orbax(path: str) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    ckpt = ocp.StandardCheckpointer()
    return ckpt.restore(Path(path).resolve())
