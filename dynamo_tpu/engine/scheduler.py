"""Continuous-batching scheduler with chunked prefill, prefix-cache reuse,
and recompute-preemption.

Pure host logic (no JAX): decides, per engine iteration, either one prefill
chunk (single sequence) or one decode step (whole running batch) — the
vLLM-style alternating schedule the reference's mocker also models
(lib/mocker: "simulates KV allocation, prefix caching, batching,
preemption"). The engine executes the plan on the ModelRunner.

Invariants:
- `computed_len` = tokens whose KV is in the pool. While RUNNING,
  computed_len == len(tokens) - 1 (the newest sampled token's KV is written
  by the next decode step).
- prefix-matched pages are complete and shared (read-only); writes happen
  only at positions >= computed_len, which always land on unshared pages.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

from dynamo_tpu.engine.kv_pool import NoSpace, PagePool
from dynamo_tpu.tokens.hashing import block_hashes, hash_block, request_seed


def _chain_seed(seq: "Sequence") -> Optional[int]:
    """Hash-chain seed: LoRA adapters and multimodal content each fork the
    block lineage (K/V depends on both; equal token ids under different
    adapters or images must never share cache blocks)."""
    return request_seed(seq.adapter, seq.mm_seed)

log = logging.getLogger("dynamo_tpu.engine.scheduler")


class SeqState(Enum):
    WAITING = "waiting"
    PREFILL = "prefill"
    RUNNING = "running"
    FINISHED = "finished"


@dataclass
class Sequence:
    request_id: str
    prompt: List[int]
    sampling: Dict[str, Any]
    stop: Dict[str, Any]
    arrival: float = 0.0
    # disaggregation (docs/design-docs/disagg-serving.md roles):
    #   None = aggregated; "prefill" = compute KV + first token then park;
    #   "decode" = KV arrives via transfer, skip prefill compute
    disagg: Optional[str] = None
    kv_import: Any = None  # opaque page payload for disagg-decode admission
    adapter: Optional[str] = None  # LoRA adapter name (None = base model)
    adapter_idx: int = 0  # resolved slot (engine sets at admission)
    logit_bias: Any = None  # [[token_id, bias], ...] (OpenAI logit_bias)
    # multimodal: embeddings for image-placeholder positions (np [n, E]),
    # their absolute prompt positions, and a content hash for KV isolation
    mm_embeds: Any = None
    mm_positions: Any = None
    mm_seed: Optional[int] = None
    # guided decoding: wire spec (dict), compiled GuidedMatcher, DFA state
    guided: Any = None
    guided_m: Any = None
    guided_s: int = 0
    state: SeqState = SeqState.WAITING
    tokens: List[int] = field(default_factory=list)  # prompt + generated
    pages: List[int] = field(default_factory=list)
    computed_len: int = 0
    n_shared_pages: int = 0  # leading pages from prefix-cache hits
    hash_chain: List[int] = field(default_factory=list)  # registered block hashes
    finish_reason: Optional[str] = None
    n_preemptions: int = 0
    n_prompt0: int = 0  # original prompt length (preemption rewrites prompt)
    # latency spine (runtime/flight_recorder.py docs): locally-measured
    # phase durations, seeded with upstream-hop stamps from ctx.metadata
    # and attached to the final emitted item as item["phases"]
    phases: Dict[str, float] = field(default_factory=dict)
    # causal trace: the traceparent this request arrived with (route
    # span); the engine synthesizes the worker's queue/onboard/prefill/
    # stream spans under it retroactively at finish
    tp: Optional[str] = None
    # deepest KV tier the admission onboard touched (G2/G3/G4) — labels
    # the worker.kv_onboard span
    onboard_tier: Optional[str] = None
    itl: List[float] = field(default_factory=list)  # bounded ITL samples
    t_last_emit: float = 0.0  # monotonic time of the last token emission
    # speculative decoding: draft tokens proposed for THIS iteration
    # (engine sets before step_plan; the scheduler trims them to the
    # mixed token budget; the engine consumes and clears after verify)
    spec_draft: List[int] = field(default_factory=list)
    # tree speculation: EXTRA candidate branches beyond spec_draft (which
    # is branch 0). Each rides the verify dispatch as its own segment on
    # a forked page table sharing the trunk; the scheduler charges every
    # branch's tokens against the mixed pool and sheds branches before
    # it trims the primary draft (a branch is strictly optional work)
    spec_tree: List[List[int]] = field(default_factory=list)
    # fork-on-branch (n>1 sampling): the parent carries n_branches; each
    # forked sibling carries branch_of=<parent request_id> and its choice
    # index, and shares the parent's trunk pages copy-on-write
    n_branches: int = 1
    branch_of: Optional[str] = None
    branch_index: int = 0
    # set after the parent's first prefill forks (or fails to fork) its
    # siblings: a preempted parent re-prefills, and re-forking would emit
    # duplicate finish items for choice indices that already streamed
    branches_spawned: bool = False

    @property
    def n_generated(self) -> int:
        return len(self.tokens) - self.n_prompt0

    @property
    def prompt_remaining(self) -> int:
        return max(0, len(self.prompt) - self.computed_len)


@dataclass
class PrefillPlan:
    seq: Sequence
    chunk: List[int]
    start_pos: int
    is_last_chunk: bool


@dataclass
class DecodePlan:
    seqs: List[Sequence]
    n_steps: int = 1  # fused decode iterations (multi-step decode)


@dataclass
class MixedPlan:
    """One engine iteration that co-schedules the running decode batch
    with a token-budgeted SET of prefill chunks (vLLM-style chunked
    prefill, extended to ragged packing — the semantics the reference's
    planner models, docs/design-docs/planner-design.md:262). Decode runs
    first so ITL never waits behind prompt processing; the chunks come
    from distinct PREFILL sequences and their combined length is capped
    at `mixed_prefill_tokens`, so the prefill cost per iteration stays
    bounded no matter how many prompts are in flight."""

    prefills: List[PrefillPlan]
    decode: DecodePlan

    @property
    def prefill(self) -> PrefillPlan:
        """Oldest chunk — compatibility accessor for single-chunk-era
        call sites (and the natural chunk for single-chunk fallbacks)."""
        return self.prefills[0]


@dataclass
class SchedulerStats:
    """Per-iteration ForwardPassMetrics feed (planner observes these)."""

    n_waiting: int = 0
    n_running: int = 0
    scheduled_tokens: int = 0
    kv_usage: float = 0.0


class Scheduler:
    def __init__(
        self,
        pool: PagePool,
        *,
        max_batch: int = 64,
        chunk_size: int = 512,
        max_seq_pages: int = 128,
        enable_prefix_cache: bool = True,
        decode_steps: int = 1,
        mixed_prefill_tokens: int = 256,
        mixed_prefill_seqs: int = 8,
        mixed_min_chunk: int = 16,
        host_tier=None,  # HostKvPool-like: .match(hashes) -> n
        host_onboard=None,  # cb(pages, hashes, seq=None) -> bool (G2→G1)
        max_seq_tokens: int = 0,  # model context length (0 = page cap only)
        spec_max_tokens: int = 0,  # per-iteration cap on speculative
        #   draft tokens (0 = bounded by the mixed pool leftover alone)
        spec_seg_budget: int = 0,  # sampled-row slots one ragged dispatch
        #   offers (decode rows + chunks + verify tokens); 0 = unbounded
    ):
        self.pool = pool
        self.max_batch = max_batch
        self.chunk_size = chunk_size
        self.max_seq_pages = max_seq_pages
        # rope-validity cap: page capacity bounds what FITS, the model's
        # max_seq_len bounds what is NUMERICALLY MEANINGFUL — a request
        # without max_tokens must stop at the context limit, not push
        # positions past the rope table into garbage logits
        self.max_seq_tokens = int(max_seq_tokens or 0)
        self.enable_prefix_cache = enable_prefix_cache
        self.decode_steps = decode_steps
        # co-scheduling budget: when decode work exists, this is the POOL
        # of prefill tokens per iteration, fair-shared across up to
        # `mixed_prefill_seqs` PREFILL sequences (oldest first, at least
        # `mixed_min_chunk` tokens each) and run IN THE SAME iteration as
        # the decode dispatch (0 = legacy strict prefill-first
        # alternation; mixed_prefill_seqs=1 = legacy single-chunk cap).
        # With no running sequences the full chunk_size still applies —
        # the budget trades TTFT for bounded ITL only when both compete.
        self.mixed_prefill_tokens = mixed_prefill_tokens
        self.mixed_prefill_seqs = max(1, mixed_prefill_seqs)
        self.mixed_min_chunk = max(1, mixed_min_chunk)
        self.spec_max_tokens = max(0, spec_max_tokens)
        self.spec_seg_budget = max(0, spec_seg_budget)
        self.host_tier = host_tier
        self.host_onboard = host_onboard
        self.waiting: deque[Sequence] = deque()
        self.active: List[Sequence] = []
        self.stats = SchedulerStats()
        # prompt tokens served from warm KV (prefix/tree reuse): these
        # never charge the mixed_prefill_tokens pool — chunking starts at
        # computed_len, so only the un-reused suffix is prefill work
        self.reused_prefix_tokens = 0
        self.prompt_tokens_total = 0  # denominator for the tree hit rate

    # -- API ---------------------------------------------------------------
    def add(self, seq: Sequence) -> None:
        seq.tokens = list(seq.prompt)
        seq.n_prompt0 = len(seq.prompt)
        self.waiting.append(seq)

    def abort(self, request_id: str) -> None:
        for i, s in enumerate(self.active):
            if s.request_id == request_id:
                self._finish(s, "cancelled")
                return
        for s in list(self.waiting):
            if s.request_id == request_id:
                s.state = SeqState.FINISHED
                s.finish_reason = "cancelled"
                self.waiting.remove(s)
                return

    def has_work(self) -> bool:
        return bool(self.waiting or self.active)

    def step_plan(self) -> Optional[PrefillPlan | DecodePlan | MixedPlan]:
        """Admit what fits, then plan this iteration's work.

        With `mixed_prefill_tokens > 0` the plan co-schedules: the whole
        running batch decodes every iteration, and a token-budgeted set
        of prefill chunks from distinct PREFILL sequences rides along
        (MixedPlan). The budget is fair-shared oldest-first with a
        per-seq minimum so one long prompt cannot starve the rest, and
        leftover share from short prompts flows to the next in line.
        Strict prefill-first alternation (mixed_prefill_tokens=0) stalls
        every decode for the full chunk pipeline of each arriving
        prompt — the ITL inflation the reference planner's
        chunked-prefill model exists to avoid."""
        self._admit()
        prefill_seqs = [s for s in self.active if s.state == SeqState.PREFILL]
        prefill_seq = prefill_seqs[0] if prefill_seqs else None
        running = [s for s in self.active if s.state == SeqState.RUNNING]
        if prefill_seq is not None and (
            not running or self.mixed_prefill_tokens <= 0
        ):
            return self._plan_prefill(prefill_seq)
        if not running:
            self._update_stats(0)
            return None
        # fuse up to decode_steps iterations, bounded by the per-seq budget
        # remaining (max_tokens / context cap) so fused steps aren't wasted
        cap = self.max_seq_pages * self.pool.page_size
        if self.max_seq_tokens:
            cap = min(cap, self.max_seq_tokens)
        n_steps = self.decode_steps
        for s in running:
            budget = min(
                cap - s.computed_len,
                int((s.stop or {}).get("max_tokens", 1 << 30)) - s.n_generated,
            )
            n_steps = min(n_steps, max(1, budget))
        # prefill chunks claim the pool FIRST (planning is side-effect
        # free) so a speculation burst can never starve real prefills —
        # verify rows are charged from the pool's leftover only
        pplans = self._plan_prefills(prefill_seqs) if prefill_seq else []
        self._trim_spec(running, pplans, cap)
        spec_tokens = sum(self._spec_cost(s) for s in running)
        if spec_tokens:
            # verify rows and fused multi-step decode don't mix: a verify
            # dispatch already advances speculating rows by up to K+1
            n_steps = 1
        running = self._ensure_decode_capacity(running, lookahead=n_steps)
        if not running:
            if prefill_seq is not None:
                return self._plan_prefill(prefill_seq)
            self._update_stats(0)
            return None
        spec_tokens = sum(self._spec_cost(s) for s in running)
        if prefill_seq is None:
            self._update_stats(len(running) * n_steps + spec_tokens)
            return DecodePlan(running, n_steps)
        self._update_stats(
            len(running) * n_steps + spec_tokens
            + sum(len(p.chunk) for p in pplans)
        )
        return MixedPlan(prefills=pplans, decode=DecodePlan(running, n_steps))

    @staticmethod
    def _spec_cost(s: Sequence) -> int:
        """Charged verify tokens for one sequence: the primary draft's
        tokens (its +1 verify position is the row's own decode slot)
        plus EVERY token of every extra tree branch (a branch row's
        position-0 entry has no decode slot to hide behind — all
        len(b)+1 entries are extra flat tokens and sampled rows; the
        twin bills them identically, keeping tree A/Bs honest)."""
        return len(s.spec_draft) + sum(len(b) + 1 for b in s.spec_tree)

    def _trim_spec(
        self, running: List[Sequence], pplans: List[PrefillPlan], cap: int
    ) -> None:
        """Fit this iteration's draft tokens to the budgets that keep the
        verify dispatch inside the registered compile bucket: drafted
        tokens charge the `mixed_prefill_tokens` pool AFTER prefill
        chunks took their share (the verified +1 token per row is the
        row's own decode slot), an optional absolute per-iteration cap,
        and the ragged dispatch's sampled-row slots when the engine set
        one. Per sequence, a draft is also clipped to the tokens the
        request can still legally generate."""
        if self.mixed_prefill_tokens <= 0:
            for s in running:
                s.spec_draft = []
                s.spec_tree = []
            return
        left = self.mixed_prefill_tokens - sum(len(p.chunk) for p in pplans)
        if self.spec_max_tokens:
            left = min(left, self.spec_max_tokens)
        seg_left = None
        if self.spec_seg_budget:
            # one sampled-row slot per decode row and per chunk; each
            # drafted token needs one more (its verify position is gathered)
            seg_left = self.spec_seg_budget - len(running) - len(pplans)
        for s in running:
            if not s.spec_draft:
                s.spec_tree = []  # branches never ride without a primary
                continue
            take = min(len(s.spec_draft), max(0, left))
            if seg_left is not None:
                take = min(take, max(0, seg_left))
            # KV for fed draft tokens lands at computed_len+1 .. +take:
            # stay inside the page/context cap
            take = min(take, max(0, cap - s.computed_len - 1))
            remaining = (
                int((s.stop or {}).get("max_tokens", 1 << 30)) - s.n_generated
            )
            take = min(take, max(0, remaining))
            if take < len(s.spec_draft):
                # the primary draft itself was trimmed — branches are
                # strictly optional, shed them all before clipping it
                s.spec_tree = []
            s.spec_draft = s.spec_draft[:take]
            left -= take
            if seg_left is not None:
                seg_left -= take
            # extra tree branches: each costs len(b)+1 flat tokens AND
            # len(b)+1 sampled-row slots (no decode slot of its own) plus
            # one ragged segment; shed whole branches from the tail when
            # the leftover can't carry them. Branches longer than the
            # (possibly clipped) primary are clipped to it — the fork's
            # page capacity is only guaranteed that far.
            kept: List[List[int]] = []
            for b in s.spec_tree:
                b = b[:take]
                cost = len(b) + 1
                if not b or cost > max(0, left) or (
                    seg_left is not None and cost > max(0, seg_left)
                ):
                    continue
                kept.append(b)
                left -= cost
                if seg_left is not None:
                    seg_left -= cost
            s.spec_tree = kept

    # -- admission ---------------------------------------------------------
    def _admit(self) -> None:
        while self.waiting and len(self.active) < self.max_batch:
            seq = self.waiting[0]
            if not self._try_allocate(seq):
                break
            self.waiting.popleft()
            self.active.append(seq)
            seq.state = SeqState.PREFILL
            # latency spine: WAITING -> PREFILL transition ends queue_wait
            # (first admission only — preemption re-admits don't reset it)
            if seq.arrival and "queue_wait_s" not in seq.phases:
                seq.phases["queue_wait_s"] = max(
                    0.0, time.monotonic() - seq.arrival)

    def _try_allocate(self, seq: Sequence) -> bool:
        PS = self.pool.page_size
        prompt = seq.prompt
        matched_pages: List[int] = []
        hashes: List[int] = []
        use_cache = self.enable_prefix_cache and seq.n_preemptions == 0
        max_shared = (len(prompt) - 1) // PS
        if use_cache:
            matched_pages, hashes = self.pool.match_prefix(prompt, _chain_seed(seq))
            # never share the page containing the final prompt token: its
            # logits must be recomputed, so cap the match below it
            while len(matched_pages) > max_shared:
                self.pool.release([matched_pages.pop()])
                hashes.pop()

        # G2 host-tier continuation: blocks beyond the device match that the
        # host pool holds get onboarded into freshly-allocated pages
        host_n = 0
        host_hashes: List[int] = []
        if use_cache and self.host_tier is not None and self.host_onboard is not None:
            all_hashes = block_hashes(prompt, PS, _chain_seed(seq))
            candidates = all_hashes[len(matched_pages):max_shared]
            host_n = self.host_tier.match(candidates)
            host_hashes = candidates[:host_n]

        match_len = len(matched_pages) * PS
        # pages for the rest of the prompt plus the first generated token
        need = -(-(len(prompt) + 1) // PS) - len(matched_pages)
        try:
            fresh = self.pool.alloc(need)
        except NoSpace:
            self.pool.release(matched_pages)
            return False

        if host_n:
            t_onboard = time.monotonic()
            if self.host_onboard(fresh[:host_n], host_hashes, seq):
                # latency spine: lower-tier KV promotion paid at admission
                seq.phases["kv_onboard_s"] = (
                    seq.phases.get("kv_onboard_s", 0.0)
                    + (time.monotonic() - t_onboard))
                parent = hashes[-1] if hashes else _chain_seed(seq)
                for page, h in zip(fresh[:host_n], host_hashes):
                    canonical = self.pool.register(page, h, parent)
                    if canonical != page:  # raced with another registration
                        self.pool._ref_inc(canonical)
                        self.pool.release([page])
                        fresh[fresh.index(page)] = canonical
                    parent = h
                hashes = hashes + host_hashes
                match_len = (len(matched_pages) + host_n) * PS

        seq.pages = matched_pages + fresh
        seq.n_shared_pages = len(matched_pages)
        seq.hash_chain = hashes
        seq.computed_len = match_len
        self.reused_prefix_tokens += match_len
        if seq.n_preemptions == 0:  # re-admits would double-count
            self.prompt_tokens_total += len(prompt)
        return True

    # -- prefill -----------------------------------------------------------
    def _plan_prefill(
        self, seq: Sequence, max_tokens: Optional[int] = None
    ) -> PrefillPlan:
        start = seq.computed_len
        budget = self.chunk_size if max_tokens is None else min(
            self.chunk_size, max(1, max_tokens)
        )
        end = min(len(seq.prompt), start + budget)
        return PrefillPlan(
            seq=seq,
            chunk=seq.prompt[start:end],
            start_pos=start,
            is_last_chunk=end == len(seq.prompt),
        )

    def _plan_prefills(self, cands: List[Sequence]) -> List[PrefillPlan]:
        """Fair-share the `mixed_prefill_tokens` pool across up to
        `mixed_prefill_seqs` PREFILL sequences, oldest first.

        Each packed sequence is offered at least `mixed_min_chunk`
        tokens (so progress is never sliced to nothing under load) and
        at most its equal share of what is left — a long prompt at the
        head of the line cannot drain the pool, and budget a short
        prompt leaves unused flows to the sequences behind it."""
        plans: List[PrefillPlan] = []
        left = self.mixed_prefill_tokens
        for i, seq in enumerate(cands):
            if left <= 0 or len(plans) >= self.mixed_prefill_seqs:
                break
            slots = min(len(cands) - i, self.mixed_prefill_seqs - len(plans))
            share = max(self.mixed_min_chunk, left // max(1, slots))
            plan = self._plan_prefill(seq, max_tokens=min(share, left))
            if plan.chunk:
                plans.append(plan)
                left -= len(plan.chunk)
        return plans

    def complete_prefill(self, plan: PrefillPlan) -> None:
        seq = plan.seq
        seq.computed_len += len(plan.chunk)
        self._register_complete_pages(seq)
        if plan.is_last_chunk:
            seq.state = SeqState.RUNNING

    def park(self, seq: Sequence) -> None:
        """Disagg-prefill: KV computed; hold pages (still ref'd) for the
        decode worker's pull, out of the active set."""
        seq.state = SeqState.FINISHED
        seq.finish_reason = "prefill_complete"
        if seq in self.active:
            self.active.remove(seq)

    def release_parked(self, seq: Sequence) -> None:
        self.pool.release(seq.pages)
        seq.pages = []

    def admit_with_kv(self, seq: Sequence) -> bool:
        """Disagg-decode admission: allocate pages for the full (computed)
        prompt; caller imports transferred KV into the non-shared pages and
        the sequence starts RUNNING with no prefill pass.

        The prompt's last token is the prefill-sampled token whose KV is
        *not* yet computed, so computed_len = len(prompt) - 1."""
        if len(self.active) >= self.max_batch:
            return False
        if not self._try_allocate(seq):
            return False
        seq.computed_len = len(seq.prompt) - 1
        seq.state = SeqState.RUNNING
        self.active.append(seq)
        self._register_complete_pages(seq)
        return True

    def adopt_branch(
        self, branch: Sequence, parent: Sequence, pages: List[int]
    ) -> bool:
        """Admit a fork-on-branch sibling directly into the running batch.

        The caller (engine._fork_branches) already fork_table'd the
        parent's pages — the shared trunk is ref-bumped and the partial
        tail copied — so the branch starts exactly where the parent is:
        same computed KV, same hash chain, one prefill-sampled token away
        from its first decode step. No prefill pass, no allocation."""
        if len(self.active) >= self.max_batch:
            self.pool.release(pages)
            return False
        branch.tokens = list(parent.tokens)
        branch.n_prompt0 = parent.n_prompt0
        branch.pages = pages
        branch.computed_len = parent.computed_len
        branch.n_shared_pages = parent.n_shared_pages
        branch.hash_chain = list(parent.hash_chain)
        branch.state = SeqState.RUNNING
        self.active.append(branch)
        return True

    # -- decode ------------------------------------------------------------
    def _ensure_decode_capacity(
        self, running: List[Sequence], lookahead: int = 1
    ) -> List[Sequence]:
        """Each running seq needs page slots for positions computed_len ..
        computed_len+lookahead-1; on pool exhaustion preempt the youngest
        sequences (recompute-style)."""
        survivors: List[Sequence] = []
        for seq in running:
            if seq.state != SeqState.RUNNING:  # preempted by an earlier turn
                continue
            # a speculating row writes KV for its fed draft tokens at
            # computed_len+1 .. +K in the SAME dispatch, so its lookahead
            # is the draft length + 1, not the fused step count
            last_pos = seq.computed_len + max(
                lookahead, len(seq.spec_draft) + 1
            ) - 1
            while True:
                need = last_pos // self.pool.page_size + 1 - len(seq.pages)
                if need <= 0:
                    survivors.append(seq)
                    break
                try:
                    seq.pages.extend(self.pool.alloc(need))
                    survivors.append(seq)
                    break
                except NoSpace:
                    victim = self._pick_victim(exclude=seq)
                    if victim is None:
                        self._preempt(seq)
                        break
                    self._preempt(victim)
                    if victim in survivors:
                        survivors.remove(victim)
        return survivors

    def _pick_victim(self, exclude: Sequence) -> Optional[Sequence]:
        for seq in reversed(self.active):  # youngest first
            if seq is not exclude and seq.state == SeqState.RUNNING:
                return seq
        return None

    def _preempt(self, seq: Sequence) -> None:
        log.info("preempting %s (recompute)", seq.request_id)
        self.pool.release(seq.pages)
        seq.pages = []
        seq.hash_chain = []
        seq.n_shared_pages = 0
        seq.computed_len = 0
        seq.n_preemptions += 1
        seq.spec_draft = []  # stale drafts must not ride the re-admission
        seq.spec_tree = []
        seq.state = SeqState.WAITING
        # re-admit with prompt = all tokens so far (already-emitted ones are
        # not re-emitted; generation resumes with the next sampled token)
        seq.prompt = list(seq.tokens)
        self.active.remove(seq)
        self.waiting.appendleft(seq)

    def complete_decode(
        self, seq: Sequence, new_token: int, advance_computed: bool = True
    ) -> Optional[str]:
        """Append a sampled token; returns finish_reason if the engine-level
        stop fires (frontend-level stop strings are handled downstream).

        advance_computed=True for decode steps (the step wrote the fed
        token's KV at position computed_len); False for the token sampled
        from prefill logits (its KV is written by the *next* decode step) —
        the invariant computed_len == len(tokens) - 1 must hold either way.
        """
        if advance_computed:
            seq.computed_len += 1
        seq.tokens.append(new_token)
        self._register_complete_pages(seq)

        stop = seq.stop or {}
        reason = None
        if (
            not stop.get("ignore_eos")
            and new_token in (stop.get("stop_ids") or [])
            and seq.n_generated > int(stop.get("min_tokens") or 0)
        ):
            reason = "stop"
        elif seq.n_generated >= int(stop.get("max_tokens", 1 << 30)):
            reason = "length"
        elif len(seq.tokens) >= self.max_seq_pages * self.pool.page_size:
            reason = "length"
        elif self.max_seq_tokens and len(seq.tokens) >= self.max_seq_tokens:
            reason = "length"
        if reason:
            self._finish(seq, reason)
        return reason

    def _finish(self, seq: Sequence, reason: str) -> None:
        seq.state = SeqState.FINISHED
        seq.finish_reason = reason
        self.pool.release(seq.pages)
        seq.pages = []
        seq.spec_draft = []
        seq.spec_tree = []
        if seq in self.active:
            self.active.remove(seq)

    # -- prefix registration ----------------------------------------------
    def _register_complete_pages(self, seq: Sequence) -> None:
        """Register pages that became complete (content-addressed) so other
        requests can share them; source of router 'store' events."""
        if not self.enable_prefix_cache:
            return
        PS = self.pool.page_size
        n_complete = min(seq.computed_len // PS, len(seq.pages))
        while len(seq.hash_chain) < n_complete:
            i = len(seq.hash_chain)
            parent = seq.hash_chain[-1] if seq.hash_chain else _chain_seed(seq)
            h = hash_block(parent, seq.tokens[i * PS : (i + 1) * PS])
            canonical = self.pool.register(seq.pages[i], h, parent)
            if canonical != seq.pages[i]:
                # another seq registered this block first; swap to the
                # canonical page and free ours
                self.pool._ref_inc(canonical)
                self.pool.release([seq.pages[i]])
                seq.pages[i] = canonical
            seq.hash_chain.append(h)

    # -- stats -------------------------------------------------------------
    def _update_stats(self, scheduled: int) -> None:
        self.stats = SchedulerStats(
            n_waiting=len(self.waiting),
            n_running=len([s for s in self.active if s.state == SeqState.RUNNING]),
            scheduled_tokens=scheduled,
            kv_usage=self.pool.usage(),
        )
