"""Speculative decoding: draft-model propose, single fused target verify,
lossless accept/resample — multi-round, entirely on device.

TPU-first shape of the classic scheme (Leviathan et al.): the draft model
runs gamma cheap S=1 decode steps, then the target verifies all gamma+1
positions in ONE S=gamma+1 forward — converting gamma sequential HBM-bound
target steps into a single compute-dense MXU pass. R rounds are fused in a
`lax.scan` with on-device position/token feedback, so a dispatch costs one
host sync for up to R*(gamma+1) tokens (the per-dispatch sync dominates on
remote-TPU links).

Losslessness: tokens are accepted with probability min(1, p(x)/q(x)) and
the first rejection resamples from norm(max(p - q, 0)), where p/q are the
EXACT filtered distributions `engine.sampling.sample` draws from
(temperature/top-k/top-p applied, greedy = one-hot) — the output stream is
distributed identically to plain decoding of the target model. Greedy
requests therefore reproduce plain greedy decoding token-for-token,
regardless of draft quality.

KV discipline: the verify pass writes target KV for all gamma+1 proposed
positions; entries past the accepted prefix are stale but are never read
(kv_lens masks attention) and are overwritten by the next round's writes at
those positions — same for the draft pool. The draft model owns parallel
KV pools addressed by the SAME page tables, so block management, prefix
sharing, and preemption need no extra bookkeeping.

The reference framework inherits speculative decoding from its delegated
engines (vLLM/TRT-LLM spec-decode configs surfaced through
components/src/dynamo/vllm flags); this is the native TPU implementation.

Relation to the host-side deterministic path: `accept_and_finalize` with
q = one-hot(draft) degenerates to `ngram_draft.accept_deterministic`
(proven equivalent by tests/test_spec_decode.py), and
`ngram_draft.accept_tree` is that same specialization walked down a trie
of candidate branches — each branch's verify row is an independent
ragged segment on a forked page table, and identical branch prefixes
sample identically, so the lowest-live-branch walk emits target samples
of exactly the emitted prefix at every depth (distribution-preserving
at any temperature; see docs/spec_decode.md).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.engine.sampling import SamplingParams, filtered_probs
from dynamo_tpu.models import llama

# PRNG fold tags: keep spec streams disjoint from plain sample() (which
# folds only the step index) and from each other
_TAG_DRAFT = 1_000_000
_TAG_ACCEPT = 2_000_000
_TAG_FINAL = 3_000_000


def _per_row_key(key_data: jax.Array, step, tag):
    key = jax.random.wrap_key_data(key_data, impl="threefry2x32")
    return jax.random.fold_in(jax.random.fold_in(key, step), tag)


def _categorical_rows(key: SamplingParams, probs: jax.Array, step, tag) -> jax.Array:
    """Per-row categorical draw from explicit probabilities [B, K] → [B].
    One-hot rows (greedy) come out deterministic."""

    def draw(key_data, row):
        return jax.random.categorical(_per_row_key(key_data, step, tag), jnp.log(row))

    return jax.vmap(draw)(key.key, probs).astype(jnp.int32)


def accept_and_finalize(
    drafts: jax.Array,  # [B, g] proposed token ids
    q_d: jax.Array,  # [B, g] draft prob of each proposed token
    q_on_t: jax.Array,  # [B, g, K] draft probs evaluated on target candidates
    t_idx: jax.Array,  # [B, g+1, K] target candidate token ids
    t_probs: jax.Array,  # [B, g+1, K] target probs (the sampling dist)
    sampling: SamplingParams,
    step,
) -> Tuple[jax.Array, jax.Array]:
    """Pure accept/resample math → (out_tokens [B, g+1], counts [B]).
    out_tokens[:, :n_acc] are accepted drafts; out_tokens[:, n_acc] is the
    rejection-resample (or the bonus token when everything was accepted);
    columns past counts are junk. Separated from the model loop so its
    distribution-preservation is unit-testable in bulk."""
    B, g1, K = t_probs.shape
    g = g1 - 1

    # p(d_i): target prob of draft token i (0 when outside target's
    # candidate set → certain rejection)
    match = t_idx[:, :g, :] == drafts[:, :, None]  # [B, g, K]
    p_d = jnp.sum(jnp.where(match, t_probs[:, :g, :], 0.0), axis=-1)

    def row_uniform(key_data):
        return jax.random.uniform(_per_row_key(key_data, step, _TAG_ACCEPT), (max(g, 1),))

    u = jax.vmap(row_uniform)(sampling.key)[:, :g]  # [B, g]
    accept = u < p_d / jnp.maximum(q_d, 1e-30)
    acc_prefix = jnp.cumprod(accept.astype(jnp.int32), axis=1)  # [B, g]
    n_acc = jnp.sum(acc_prefix, axis=1)  # [B] length of accepted prefix

    # residual distribution at the first rejected position r = n_acc:
    # norm(max(p_r - q_r, 0)); padding q with zeros at position g makes the
    # all-accepted case fall out as the plain bonus draw from p_{g+1}
    q_ext = jnp.concatenate([q_on_t, jnp.zeros((B, 1, K), q_on_t.dtype)], axis=1)
    sel = n_acc[:, None, None]
    p_r = jnp.take_along_axis(t_probs, sel, axis=1)[:, 0]  # [B, K]
    q_r = jnp.take_along_axis(q_ext, sel, axis=1)[:, 0]
    resid = jnp.maximum(p_r - q_r, 0.0)
    rs = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(rs > 1e-12, resid / jnp.maximum(rs, 1e-30), p_r)

    j = _categorical_rows(sampling, resid, step, _TAG_FINAL)
    idx_r = jnp.take_along_axis(t_idx, sel, axis=1)[:, 0]  # [B, K]
    final = jnp.take_along_axis(idx_r, j[:, None], axis=1)[:, 0].astype(jnp.int32)

    out = jnp.concatenate([drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
    out = out.at[jnp.arange(B), n_acc].set(final)
    return out, (n_acc + 1).astype(jnp.int32)


def spec_rounds(
    config,
    draft_config,
    decode_impl: str,  # draft S=1 attention impl ("jnp" | "pallas")
    verify_impl: str,  # target S=g+1 attention impl
    mesh,  # for sharded pallas attention on TP meshes (None = single dev)
    gamma: int,
    n_rounds: int,
    params,
    draft_params,
    tokens0: jax.Array,  # [B] current last token per seq
    positions0: jax.Array,  # [B] its write position (-1 = padding slot)
    k_pool,
    v_pool,
    dk_pool,
    dv_pool,
    page_table: jax.Array,  # [B, MP]
    sampling: SamplingParams,
    step0,
    lora=None,  # target-model multi-LoRA tree; the draft proposes base-only
    adapter_idx=None,  # [B]
):
    """R speculative rounds fused in one jit. Returns
    (tokens [B, R, gamma+1], counts [B, R], k_pool, v_pool, dk_pool,
    dv_pool). Page tables must cover positions0 + R*(gamma+1) slots.

    With LoRA, only the target verify applies adapters (authoritative for
    the output distribution); the draft proposes from the base model, which
    costs acceptance rate on heavily-adapted models but never correctness."""
    B = tokens0.shape[0]

    def round_body(carry, r):
        tok, pos, kp, vp, dkp, dvp = carry
        step = step0 + r

        # -- draft: sequential S=1 proposals. The scan runs gamma+1 steps:
        # step i writes the FED token's KV at pos+i, so the extra step
        # writes d_gamma's KV at pos+gamma — without it, a fully-accepted
        # round leaves a permanent zero-KV hole at that position (the next
        # round starts writing at pos+gamma+1) and acceptance decays
        # exactly when the draft is good. The last step's proposal is
        # discarded.
        def draft_body(dc, i):
            t, dkp, dvp = dc
            p_i = jnp.where(pos < 0, -1, pos + i)
            kvl = jnp.where(pos < 0, 0, pos + i + 1)
            logits, dkp, dvp = llama.forward(
                draft_config, draft_params, t[:, None], p_i[:, None],
                dkp, dvp, page_table, kvl, attn_impl=decode_impl, mesh=mesh,
            )
            idx, probs = filtered_probs(logits[:, 0], sampling)
            j = _categorical_rows(sampling, probs, step, _TAG_DRAFT + i)
            d = jnp.take_along_axis(idx, j[:, None], axis=1)[:, 0].astype(jnp.int32)
            qd = jnp.take_along_axis(probs, j[:, None], axis=1)[:, 0]
            return (d, dkp, dvp), (d, idx, probs, qd)

        (_, dkp, dvp), (d_seq, d_idx, d_probs, q_d) = lax.scan(
            draft_body, (tok, dkp, dvp), jnp.arange(gamma + 1, dtype=jnp.int32)
        )
        drafts = d_seq.T[:, :gamma]  # [B, g]
        d_idx = jnp.moveaxis(d_idx, 0, 1)[:, :gamma]  # [B, g, K]
        d_probs = jnp.moveaxis(d_probs, 0, 1)[:, :gamma]
        q_d = q_d.T[:, :gamma]  # [B, g]

        # -- target: one S=gamma+1 verify pass -----------------------------
        ver_toks = jnp.concatenate([tok[:, None], drafts], axis=1)  # [B, g+1]
        offs = jnp.arange(gamma + 1, dtype=jnp.int32)
        ver_pos = jnp.where(pos[:, None] < 0, -1, pos[:, None] + offs)
        kvl = jnp.where(pos < 0, 0, pos + gamma + 1)
        logits, kp, vp = llama.forward(
            config, params, ver_toks, ver_pos, kp, vp, page_table, kvl,
            attn_impl=verify_impl, mesh=mesh, lora=lora, adapter_idx=adapter_idx,
        )  # [B, g+1, V]
        V = logits.shape[-1]
        # penalties are not applied on the spec-decode path (the verify
        # distribution must match the draft's, and both see raw logits);
        # the fields still repeat so the NamedTuple stays well-formed
        rep = SamplingParams(
            temperature=jnp.repeat(sampling.temperature, gamma + 1),
            top_k=jnp.repeat(sampling.top_k, gamma + 1),
            top_p=jnp.repeat(sampling.top_p, gamma + 1),
            key=jnp.repeat(sampling.key, gamma + 1, axis=0),
            rep_penalty=jnp.repeat(sampling.rep_penalty, gamma + 1),
            freq_penalty=jnp.repeat(sampling.freq_penalty, gamma + 1),
            presence_penalty=jnp.repeat(sampling.presence_penalty, gamma + 1),
        )
        t_idx, t_probs = filtered_probs(logits.reshape(B * (gamma + 1), V), rep)
        K = t_idx.shape[-1]
        t_idx = t_idx.reshape(B, gamma + 1, K)
        t_probs = t_probs.reshape(B, gamma + 1, K)

        # draft distribution evaluated on the target's candidate ids
        pair = t_idx[:, :gamma, :, None] == d_idx[:, :, None, :]  # [B,g,K,K]
        q_on_t = jnp.sum(jnp.where(pair, d_probs[:, :, None, :], 0.0), axis=-1)

        out_toks, counts = accept_and_finalize(
            drafts, q_d, q_on_t, t_idx, t_probs, sampling, step
        )

        new_pos = jnp.where(pos < 0, pos, pos + counts)
        last = jnp.take_along_axis(out_toks, (counts - 1)[:, None], axis=1)[:, 0]
        return (last, new_pos, kp, vp, dkp, dvp), (out_toks, counts)

    (_, _, k_pool, v_pool, dk_pool, dv_pool), (toks, counts) = lax.scan(
        round_body,
        (tokens0, positions0, k_pool, v_pool, dk_pool, dv_pool),
        jnp.arange(n_rounds, dtype=jnp.int32),
    )
    # scan stacks rounds on axis 0 → [B, R, ...]
    return (
        jnp.moveaxis(toks, 0, 1),
        counts.T,
        k_pool,
        v_pool,
        dk_pool,
        dv_pool,
    )
