"""Host-side paged KV allocator with content-addressed prefix caching.

This is the G1 (device HBM) tier's logical block manager — the TPU analog
of the reference's in-engine prefix cache plus the kvbm-logical block
lifecycle (Reset → Partial → Complete → Registered,
docs/design-docs/kvbm-design.md:121-150):

- pages are allocated from a free list per sequence;
- when a page fills, it is *registered* under its lineage hash
  (dynamo_tpu.tokens.hashing) and becomes shareable: later requests with a
  matching prefix reuse it (ref-counted) without recompute;
- freed pages with refcount 0 stay cached (LRU) until capacity demands
  eviction;
- register/evict produce KV events (store/remove) that the worker's
  publisher forwards to the router's indexer.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dynamo_tpu.tokens.hashing import block_hashes


@dataclass
class KvEvent:
    kind: str  # "store" | "remove"
    block_hashes: List[int]
    # parent hash of the first stored block (lineage anchoring), store only
    parent_hash: Optional[int] = None
    tier: str = "device"  # "device" (G1) | "host" (G2) — router credit tiers


class NoSpace(Exception):
    """Raised when allocation fails even after eviction (caller preempts)."""


class PagePool:
    def __init__(self, num_pages: int, page_size: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self.free: List[int] = list(range(num_pages - 1, -1, -1))
        self.ref: Dict[int, int] = {}  # page -> refcount (allocated pages)
        # registered (complete, content-addressed) pages
        self.by_hash: Dict[int, int] = {}  # block_hash -> page
        self.hash_of: Dict[int, int] = {}  # page -> block_hash
        # cached = registered pages with ref 0, LRU order (evict from front)
        self.cached: "OrderedDict[int, None]" = OrderedDict()
        self.parent_of: Dict[int, Optional[int]] = {}  # hash -> parent hash
        self.events: List[KvEvent] = []
        # offload hook: cb(page, block_hash, parent_hash) invoked just
        # before an evicted page's slot is reused (KVBM G1→G2 offload)
        self.evict_hook = None
        # prefetch-pinned hashes: cached pages eviction must skip (promoted
        # speculatively for an inbound request; pins are TTL-bounded by the
        # PrefetchManager, never held across a pool reset)
        self.pinned: set = set()
        # cb(block_hash) when match_prefix claims a pinned hash (the
        # prefetch hit signal; the pin is dropped before the call)
        self.claim_hook = None
        # fork-on-branch: cb(src_page, dst_page) copies device KV when a
        # branch takes a private copy of a not-yet-complete page (CoW)
        self.copy_hook = None
        self.forks = 0  # fork_table calls (branch fan-outs)
        self.match_hit_blocks = 0  # blocks served warm by match_prefix

    # -- capacity ----------------------------------------------------------
    @property
    def n_free(self) -> int:
        # pinned pages sit in `cached` but eviction skips them, so they are
        # not allocatable headroom (pinned hashes always map to cached
        # pages: pin() requires it, claiming unpins)
        return len(self.free) + len(self.cached) - len(self.pinned)

    def usage(self) -> float:
        return 1.0 - self.n_free / self.num_pages

    # -- allocation --------------------------------------------------------
    def _pop_free(self) -> int:
        if self.free:
            return self.free.pop()
        # evict LRU cached page (offloading its contents first if hooked),
        # skipping prefetch-pinned pages — if EVERY cached page is pinned
        # the pool is genuinely out (pins are brief and TTL-bounded)
        victim = None
        for page in self.cached:
            if self.hash_of[page] not in self.pinned:
                victim = page
                break
        if victim is not None:
            del self.cached[victim]
            h = self.hash_of.pop(victim)
            del self.by_hash[h]
            parent = self.parent_of.pop(h, None)
            if self.evict_hook is not None:
                self.evict_hook(victim, h, parent)
            self.events.append(KvEvent("remove", [h]))
            return victim
        raise NoSpace("no free or evictable pages")

    def alloc(self, n: int) -> List[int]:
        if self.n_free < n:
            raise NoSpace(f"need {n} pages, have {self.n_free} evictable")
        pages = [self._pop_free() for _ in range(n)]
        for p in pages:
            self.ref[p] = 1
        return pages

    # -- prefix cache ------------------------------------------------------
    def match_prefix(
        self, tokens: List[int], parent: "Optional[int]" = None
    ) -> Tuple[List[int], List[int]]:
        """Longest cached prefix → (pages, hashes). Bumps refcounts.
        `parent` seeds the hash chain (per-adapter KV isolation)."""
        pages: List[int] = []
        hashes: List[int] = []
        for h in block_hashes(tokens, self.page_size, parent):
            page = self.by_hash.get(h)
            if page is None:
                break
            pages.append(page)
            hashes.append(h)
        for p in pages:
            self._ref_inc(p)
        for h in hashes:
            if h in self.pinned:  # prefetched block claimed by a request
                self.pinned.discard(h)
                if self.claim_hook is not None:
                    self.claim_hook(h)
        self.match_hit_blocks += len(pages)
        return pages, hashes

    # -- fork-on-branch ----------------------------------------------------
    def fork_table(self, pages: List[int], n_shared: int) -> List[int]:
        """Copy-on-write fork of a sequence's page table (n>1 sampling,
        tool-call retries, tree-speculation branch verify rows): the
        first `n_shared` pages hold KV both branches agree on and are
        shared by reference; the remainder — typically just the partial
        page being written — is duplicated into fresh pages via
        `copy_hook(src, dst)` so divergent decode never clobbers the
        sibling. Raises NoSpace before touching refcounts, so a failed
        fork leaves the parent untouched. Tree speculation forks one
        table per candidate branch each verify iteration and releases
        every loser (or swaps the winner in for the trunk) before
        committing tokens — `release` drops one ref per page, so
        trunk-shared pages survive exactly as long as some table still
        points at them (docs/spec_decode.md)."""
        n_shared = max(0, min(n_shared, len(pages)))
        tail = pages[n_shared:]
        fresh = self.alloc(len(tail)) if tail else []
        for p in pages[:n_shared]:
            self._ref_inc(p)
        if self.copy_hook is not None:
            for src, dst in zip(tail, fresh):
                self.copy_hook(src, dst)
        self.forks += 1
        return pages[:n_shared] + fresh

    def _ref_inc(self, page: int) -> None:
        if page in self.cached:
            del self.cached[page]
            self.ref[page] = 1
        else:
            self.ref[page] = self.ref.get(page, 0) + 1

    def register(self, page: int, block_hash: int, parent_hash: Optional[int]) -> int:
        """Mark a full page content-addressed. If the hash is already
        registered to another page (race between concurrent prefills of the
        same prefix), keep the existing mapping. Returns the canonical page."""
        existing = self.by_hash.get(block_hash)
        if existing is not None and existing != page:
            return existing
        self.by_hash[block_hash] = page
        self.hash_of[page] = block_hash
        self.parent_of[block_hash] = parent_hash
        self.events.append(KvEvent("store", [block_hash], parent_hash))
        return page

    def pin(self, block_hash: int) -> bool:
        """Shield a cached (registered, ref-0) page from eviction until
        unpin/claim. Pinning a hash that is not a cached page is a no-op
        (returns False) — the n_free accounting depends on the invariant."""
        page = self.by_hash.get(block_hash)
        if page is None or page not in self.cached:
            return False
        self.pinned.add(block_hash)
        return True

    def unpin(self, block_hash: int) -> None:
        self.pinned.discard(block_hash)

    def release(self, pages: List[int]) -> None:
        """Drop one reference; refcount-0 registered pages go to the LRU
        cache, unregistered ones back to the free list."""
        for p in pages:
            r = self.ref.get(p, 0) - 1
            if r > 0:
                self.ref[p] = r
                continue
            self.ref.pop(p, None)
            if p in self.hash_of:
                self.cached[p] = None  # most-recently-used end
                self.cached.move_to_end(p)
            else:
                self.free.append(p)

    def drain_events(self) -> List[KvEvent]:
        ev, self.events = self.events, []
        return ev

    def reset(self) -> None:
        """Forget every block and reference: the device pool's CONTENTS
        were lost (e.g. rebuilt after a failed donated step), so every
        cached page and in-flight allocation is garbage. Emits remove
        events for all registered hashes so router indices and lower-tier
        credits stay truthful. Callers must have failed/aborted the
        sequences that held references."""
        if self.by_hash:
            self.events.append(KvEvent("remove", list(self.by_hash)))
        self.free = list(range(self.num_pages - 1, -1, -1))
        self.ref.clear()
        self.by_hash.clear()
        self.hash_of.clear()
        self.cached.clear()
        self.parent_of.clear()
        self.pinned.clear()
