"""`python -m dynamo_tpu.mocker` — simulated worker process.

Analog of reference `python -m dynamo.mocker` (docs/dynosim/README.md:23):
registers as a real worker — real discovery, request plane, KV events, FPM
— with the engine replaced by SimRunner's TPU step-time model. Drives
router/planner/frontend testing with zero TPUs.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.engine.engine import InferenceEngine
from dynamo_tpu.frontend.protocols import ModelCard
from dynamo_tpu.mocker.sim import SimRunner, SimTiming
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging_util import configure_logging
from dynamo_tpu.worker_common import serve_worker


def parse_args(argv=None):
    p = argparse.ArgumentParser("dynamo_tpu.mocker")
    p.add_argument("--model-name", default="mock-model")
    p.add_argument("--namespace", default="dyn")
    p.add_argument("--component", default="mocker")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--num-pages", type=int, default=2048)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-seq-len", type=int, default=4096)
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--chunk-size", type=int, default=512)
    p.add_argument("--decode-steps", type=int, default=4)
    p.add_argument("--host-kv-blocks", type=int, default=0,
                   help="G2 host KV tier capacity in blocks (0 = off)")
    p.add_argument("--disk-kv-blocks", type=int, default=0,
                   help="G3 disk KV tier capacity in blocks (needs G2 on)")
    p.add_argument("--disk-kv-root", default=None)
    p.add_argument("--disk-kv-bytes", type=int, default=None,
                   help="G3 byte budget: exceeding it spills LRU blocks "
                        "to the G4 object tier (needs --obj-kv-root)")
    p.add_argument("--obj-kv-root", default=None,
                   help="G4 object-store root (fs backend / shared "
                        "mount); enables the fleet-shared KV tier")
    p.add_argument("--slice-id", default=None,
                   help="topology label: workers sharing a slice-id are "
                        "one ICI island; cross-slice pulls are DCN-class")
    p.add_argument("--kv-export-bytes", action="store_true",
                   help="export tiny real KV arrays instead of hash-only "
                        "markers, so disk-tier spills write actual files "
                        "(chaos sims corrupt them to drive quarantine)")
    p.add_argument("--kv-tier-quantize", action="store_true",
                   help="int8 + scales storage in the G2/G3 tiers (mocker "
                        "tiers are hash-only; affects byte accounting)")
    p.add_argument("--onboard-layer-groups", type=int, default=1,
                   help="stream tier onboarding in this many layer-group "
                        "slabs (1 = whole-sequence import)")
    p.add_argument("--prefetch", action="store_true",
                   help="router-hinted predictive KV promotion (needs "
                        "--host-kv-blocks > 0)")
    p.add_argument("--prefetch-max-inflight", type=int, default=4)
    p.add_argument("--prefetch-bandwidth-mbps", type=float, default=0.0)
    p.add_argument("--prefetch-hint-ttl-s", type=float, default=10.0)
    p.add_argument("--prefetch-pin-ttl-s", type=float, default=5.0)
    p.add_argument("--speed", type=float, default=1.0, help="timing scale; 0 = no sleeps")
    p.add_argument("--mixed-prefill-tokens", type=int, default=256,
                   help="per-iteration prefill token pool when co-scheduled "
                        "with decode (the prefill:decode ratio knob the "
                        "planner actuator retunes)")
    p.add_argument("--mixed-prefill-seqs", type=int, default=8,
                   help="max distinct prefills packed per iteration")
    p.add_argument("--spec-ngram", action="store_true",
                   help="n-gram speculative decoding (verify rows billed "
                        "like ragged prefill tokens)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="draft length K per speculating sequence")
    p.add_argument("--spec-max-tokens", type=int, default=0,
                   help="per-iteration drafted-token cap (0 = leftover "
                        "mixed prefill budget)")
    p.add_argument("--spec-branches", type=int, default=1,
                   help="tree speculation: candidate branches per "
                        "speculating sequence (1 = linear K drafts)")
    p.add_argument("--spec-accept-rate", type=float, default=None,
                   help="oracle drafter: corrupt the true stream per "
                        "position with prob 1-rate instead of n-gram "
                        "lookup (A/B knob for bench_spec.py)")
    p.add_argument("--decode-base-ms", type=float, default=4.0)
    p.add_argument("--recorder-size", type=int, default=4096,
                   help="flight-recorder ring capacity (0 = off)")
    p.add_argument("--anomaly-k", type=float, default=4.0)
    p.add_argument("--anomaly-dump-dir", default=None)
    p.add_argument("--anomaly-dump-last-n", type=int, default=256)
    p.add_argument("--status-port", type=int, default=0,
                   help="serve /live /health /metrics /debug/timeline here")
    p.add_argument("--digest-period", type=float, default=2.0,
                   help="fleet digest publish period in seconds (0 = off)")
    p.add_argument("--disagg-role", default=None, choices=[None, "prefill", "decode", "both"])
    p.add_argument("--discovery-backend", default=None)
    p.add_argument("--discovery-root", default=None)
    p.add_argument("--sanitize", action="store_true",
                   help="arm the runtime sanitizer (recompile tripwire, "
                        "lock-order recorder, task/pool audits; same as "
                        "DYN_SAN=1)")
    return p.parse_args(argv)


def build_mock_engine(
    args, timing=None, idle_sleep_s=None, sanitizer=None
) -> tuple[InferenceEngine, ModelCard]:
    """`timing` overrides the flag-derived SimTiming (calibrated fits from
    flight-recorder dumps); `idle_sleep_s` widens the engine thread's idle
    poll — a fleet simulator hosting hundreds of engine threads in one
    process cannot afford 500 threads waking every 2 ms. `sanitizer` is a
    pre-built (shared) runtime Sanitizer — fleet-sim passes one instance
    for all workers."""
    if timing is None:
        timing = SimTiming(speed=args.speed, decode_base_s=args.decode_base_ms / 1000.0)
    runner = SimRunner(
        num_pages=args.num_pages,
        page_size=args.page_size,
        max_pages_per_seq=-(-args.max_seq_len // args.page_size),
        timing=timing,
        spec_accept_rate=getattr(args, "spec_accept_rate", None),
        kv_export_bytes=getattr(args, "kv_export_bytes", False),
    )
    engine_kw = {}
    if idle_sleep_s is not None:
        engine_kw["idle_sleep_s"] = idle_sleep_s
    engine = InferenceEngine(
        runner, max_batch=args.max_batch, chunk_size=args.chunk_size,
        **engine_kw,
        decode_steps=args.decode_steps,
        mixed_prefill_tokens=getattr(args, "mixed_prefill_tokens", 256),
        mixed_prefill_seqs=getattr(args, "mixed_prefill_seqs", 8),
        spec_ngram=getattr(args, "spec_ngram", False),
        spec_k=getattr(args, "spec_k", 4),
        spec_max_tokens=getattr(args, "spec_max_tokens", 0),
        spec_branches=getattr(args, "spec_branches", 1),
        host_kv_blocks=getattr(args, "host_kv_blocks", 0),
        disk_kv_blocks=getattr(args, "disk_kv_blocks", 0),
        disk_kv_root=getattr(args, "disk_kv_root", None),
        disk_kv_bytes=getattr(args, "disk_kv_bytes", None),
        obj_kv_root=getattr(args, "obj_kv_root", None),
        slice_id=getattr(args, "slice_id", None),
        kv_tier_quantize=getattr(args, "kv_tier_quantize", False),
        onboard_layer_groups=getattr(args, "onboard_layer_groups", 1),
        prefetch=getattr(args, "prefetch", False),
        prefetch_max_inflight=getattr(args, "prefetch_max_inflight", 4),
        prefetch_bandwidth_mbps=getattr(args, "prefetch_bandwidth_mbps", 0.0),
        prefetch_hint_ttl_s=getattr(args, "prefetch_hint_ttl_s", 10.0),
        prefetch_pin_ttl_s=getattr(args, "prefetch_pin_ttl_s", 5.0),
        recorder_size=getattr(args, "recorder_size", 4096),
        anomaly_k=getattr(args, "anomaly_k", 4.0),
        anomaly_dump_dir=getattr(args, "anomaly_dump_dir", None),
        anomaly_dump_last_n=getattr(args, "anomaly_dump_last_n", 256),
        sanitize=getattr(args, "sanitize", None) or None,
        sanitizer=sanitizer,
    )
    card = ModelCard(
        name=args.model_name,
        tokenizer="byte",
        context_length=args.max_seq_len,
        kv_block_size=args.page_size,
    )
    return engine, card


async def async_main(args) -> None:
    configure_logging()
    kw = {}
    if args.discovery_root:
        kw["root"] = args.discovery_root
    runtime = DistributedRuntime(discovery_backend=args.discovery_backend, **kw)
    engine, card = build_mock_engine(args)
    status = None
    if args.status_port:
        from dynamo_tpu.runtime.status import StatusServer

        status = StatusServer(runtime, port=args.status_port)
        status.add_check(
            "engine", lambda: getattr(engine, "_thread", True) is not None
        )
        rec = engine.recorder
        if rec is not None and rec.enabled:
            from dynamo_tpu.runtime.flight_recorder import to_chrome_trace

            status.add_timeline(
                lambda last_n=None: to_chrome_trace(rec.snapshot(last_n))
            )
        await status.start()
    worker = await serve_worker(
        runtime, engine, card,
        namespace=args.namespace, component=args.component, endpoint=args.endpoint,
        disagg_role=args.disagg_role,
        digest_period_s=args.digest_period,
    )
    san = engine.sanitizer
    if san is not None:
        san.start_watchdog()  # event-loop lag gauge for the serve loop
    print(f"mocker serving {card.name} at {args.namespace}/{args.component}/{args.endpoint}", flush=True)
    try:
        stop_ev = asyncio.Event()
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop_ev.set)
            except NotImplementedError:  # pragma: no cover
                pass
        await stop_ev.wait()
        print("draining...", flush=True)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        if status is not None:
            await status.stop()
        await worker.stop()
        if san is not None:
            await san.stop_watchdog()
            san.audit_tasks()  # leaked fire-and-forget tasks at shutdown
        await runtime.shutdown()


def main(argv=None) -> None:
    try:
        asyncio.run(async_main(parse_args(argv)))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
