"""`python -m dynamo_tpu.mocker` — simulated worker process.

Analog of reference `python -m dynamo.mocker`: registers as a real worker
(discovery + request plane + model card) with a simulated engine. Currently
serves the EchoWorkerEngine; the TPU step-time scheduler mock replaces it in
the full mocker.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.frontend.protocols import ModelCard
from dynamo_tpu.mocker.echo import EchoWorkerEngine
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging_util import configure_logging


def parse_args(argv=None):
    p = argparse.ArgumentParser("dynamo_tpu.mocker")
    p.add_argument("--model-name", default="echo-model")
    p.add_argument("--namespace", default="dyn")
    p.add_argument("--component", default="mocker")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--token-delay-ms", type=float, default=0.0)
    p.add_argument("--discovery-backend", default=None)
    p.add_argument("--discovery-root", default=None)
    return p.parse_args(argv)


async def async_main(args) -> None:
    configure_logging()
    kw = {}
    if args.discovery_root:
        kw["root"] = args.discovery_root
    runtime = DistributedRuntime(discovery_backend=args.discovery_backend, **kw)
    card = ModelCard(name=args.model_name, tokenizer="byte")
    engine = EchoWorkerEngine(token_delay_s=args.token_delay_ms / 1000.0)
    path = f"{args.namespace}/{args.component}/{args.endpoint}"
    await runtime.serve_endpoint(path, engine, metadata={"model_card": card.to_dict()})
    print(f"mocker serving {args.model_name} at {path}", flush=True)
    try:
        await asyncio.Event().wait()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await runtime.shutdown()


def main(argv=None) -> None:
    try:
        asyncio.run(async_main(parse_args(argv)))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
