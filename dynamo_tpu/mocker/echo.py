"""Echo worker engine: a no-model completion engine speaking the real
worker protocol (PreprocessedRequest in, engine-output items out).

Mirror of reference lib/llm/src/engines.rs:77 EchoEngine — used for
frontend/runtime e2e tests and demos with zero accelerators. Generates by
replaying the prompt tokens (cycled) up to max_tokens, at a configurable
per-token delay.
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, Dict

from dynamo_tpu.frontend.protocols import engine_output
from dynamo_tpu.runtime.context import Context


class EchoWorkerEngine:
    def __init__(self, token_delay_s: float = 0.0, tokens_per_item: int = 1):
        self.token_delay_s = token_delay_s
        self.tokens_per_item = tokens_per_item

    async def generate(self, request: Dict[str, Any], context: Context) -> AsyncIterator[Any]:
        prompt = request.get("token_ids") or [0]
        stop = request.get("stop") or {}
        max_tokens = int(stop.get("max_tokens", 16))
        stop_ids = set(stop.get("stop_ids") or [])

        emitted = 0
        buf = []
        i = 0
        while emitted < max_tokens:
            if context.is_stopped:
                if buf:
                    yield engine_output(buf, None)
                yield engine_output([], "cancelled")
                return
            tok = prompt[i % len(prompt)]
            i += 1
            # never emit a stop id by accident (echoing BOS/EOS prompts)
            if tok in stop_ids:
                continue
            buf.append(tok)
            emitted += 1
            if len(buf) >= self.tokens_per_item or emitted >= max_tokens:
                finish = "length" if emitted >= max_tokens else None
                yield engine_output(buf, finish)
                buf = []
            if self.token_delay_s:
                await asyncio.sleep(self.token_delay_s)
