"""Simulated model runner with a TPU step-time model.

The mocker philosophy mirrors the reference (lib/mocker/src/lib.rs:4-9):
run the REAL scheduling stack — PagePool prefix caching, continuous-batching
Scheduler, KV events, FPM — and fake only the accelerator. SimRunner
implements ModelRunner's interface (prefill / decode_multi / sample_one),
sleeping per a linear step-time model instead of dispatching XLA programs,
so router/planner/frontend tests and CI run with zero TPUs while exercising
every byte of the orchestration path.

Timing model (fitted to v5e single-chip measurements; override per test):
  prefill(chunk)          = prefill_base_s + chunk_tokens * prefill_per_token_s
  prefill_packed(chunks)  = prefill_base_s + charged * prefill_per_token_s
                            (ONE dispatch base for the whole token-budget
                            packed set; charged = sum(chunk_tokens) under
                            prefill_cost="ragged" [default], or
                            N_bucket * S_bucket under "padded" — the
                            legacy [N, S] device path's real bill)
  decode_multi(T, batch)  = dispatch_overhead_s + T * (decode_base_s +
                            batch * decode_per_seq_s)
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional

import numpy as np


@dataclass
class SimTiming:
    prefill_base_s: float = 0.004
    prefill_per_token_s: float = 0.00004  # ~25k tok/s prefill
    decode_base_s: float = 0.004
    decode_per_seq_s: float = 0.0003
    dispatch_overhead_s: float = 0.002
    # host→device KV onboarding (import_pages): dispatch setup plus a
    # per-page DMA cost. Charged by BOTH the synchronous admission-time
    # onboard and the prefetch promotion path, so prefetch A/Bs measure
    # overlap, not a fictional free copy.
    onboard_base_s: float = 0.002
    onboard_per_page_s: float = 0.0002
    # layer-streamed onboarding (import_pages layer_groups > 1): each
    # additional layer group issues its own transfer, costing this much
    # setup on top of its share of the per-page DMA. The model is honest
    # about both sides of the trade: only the FIRST group blocks the
    # dispatch (shallow layers must be resident before prefill starts);
    # the remaining groups stream concurrently with subsequent compute,
    # but the compute that CONSUMES the pages cannot finish before the
    # deepest group lands — so the A/B win is bounded by the genuinely
    # overlappable compute, never a fictional free copy. More groups =
    # smaller blocking slice but more per-group setup overhead.
    onboard_group_base_s: float = 0.0005
    # fork-on-branch CoW: one page's KV duplicated on-device when a
    # branch takes a private copy of the shared trunk's partial tail
    page_copy_s: float = 0.0002
    # device n-gram draft ring: ONE fused append+propose dispatch per
    # speculating iteration (engine._device_draft). Billed per call, not
    # per row — the whole point of the ring is that proposal cost stops
    # scaling with batch and history length
    draft_propose_s: float = 0.0002
    speed: float = 1.0  # scale all sleeps; 0 disables (unit tests)
    # prefill_packed cost mode. "ragged" (default) charges
    # sum(chunk_tokens) — the flat-token dispatch the ragged runner path
    # actually issues. "padded" charges N_bucket x S_bucket — the legacy
    # [N, S] bucket-padded dispatch — so pre/post mocker A/Bs compare the
    # ragged kernel against what the padded device path really cost, not
    # against an already-ideal simulator.
    prefill_cost: str = "ragged"
    pack_buckets: tuple = (1, 2, 4, 8, 16, 32)
    chunk_buckets: tuple = (16, 32, 64, 128, 256, 512, 1024)

    def packed_charge_tokens(self, chunk_lens: List[int]) -> int:
        """Token count one packed-prefill dispatch is charged for."""
        if self.prefill_cost == "padded":
            n = _sat_bucket(self.pack_buckets, len(chunk_lens))
            s = _sat_bucket(self.chunk_buckets, max(chunk_lens))
            return n * s
        if self.prefill_cost != "ragged":
            raise ValueError(
                f"unknown prefill_cost {self.prefill_cost!r} "
                "(expected 'ragged' or 'padded')"
            )
        return sum(chunk_lens)

    def spec_charge_tokens(self, draft_lens: List[int]) -> int:
        """Extra flat tokens one spec-verify dispatch is charged for:
        drafted+1 per speculating row (the verify row IS a short prefill
        chunk on the ragged path), bucket-padded under "padded" exactly
        like a packed prefill would be. Rows with no draft are plain
        decode rows and charge nothing here (they are covered by the
        decode term of the dispatch)."""
        lens = [d + 1 for d in draft_lens if d > 0]
        if not lens:
            return 0
        return self.packed_charge_tokens(lens)

    def sleep(self, seconds: float) -> None:
        if self.speed > 0:
            time.sleep(seconds * self.speed)


    @classmethod
    def from_profile(cls, profile, speed: float = 1.0,
                     variant=None) -> "SimTiming":
        """Calibrate from a HARDWARE profile artifact (planner/
        hw_profile.py) — the measured counterpart of fit(): mockers then
        simulate the chip that was actually profiled, not guessed
        constants."""
        from dynamo_tpu.planner.hw_profile import load_profile, profile_fit

        if isinstance(profile, str):
            profile = load_profile(profile)
        f = profile_fit(profile, variant)
        return cls(
            prefill_base_s=f["prefill_base_s"],
            prefill_per_token_s=f["prefill_per_token_s"],
            decode_base_s=f["decode_base_s"],
            decode_per_seq_s=f["decode_per_seq_s"],
            dispatch_overhead_s=0.0,  # measured per-step times include it
            speed=speed,
        )

    @classmethod
    def fit(cls, fpm_history, decode_steps: int = 1, speed: float = 1.0) -> "SimTiming":
        """Fit the linear step-time model to observed ForwardPassMetrics
        (real engine runs → calibrated mocker; the reference's DynoSim
        fits its simulator from profiling data the same way). Accepts
        dataclasses or plain dicts (FPM events off the event plane)."""

        def get(m, k):
            return getattr(m, k, None) if not isinstance(m, dict) else m.get(k)

        def lstsq(xs, ys, d0, s0):
            # shared fitting routine with the hardware profiler
            from dynamo_tpu.planner.hw_profile import fit_line

            return fit_line(zip(xs, ys), d0, s0)

        dec = [(get(m, "n_running"), get(m, "wall_time_s"))
               for m in fpm_history if get(m, "kind") == "decode"]
        pre = [(get(m, "scheduled_tokens"), get(m, "wall_time_s"))
               for m in fpm_history if get(m, "kind") == "prefill"]
        base = cls()
        T = max(decode_steps, 1)
        # fallbacks are expressed per-DISPATCH (x T) so the division below
        # lands back on the per-step defaults when there's nothing to fit
        d_int, d_slope = lstsq([x for x, _ in dec], [y for _, y in dec],
                               base.decode_base_s * T, base.decode_per_seq_s * T)
        p_int, p_slope = lstsq([x for x, _ in pre], [y for _, y in pre],
                               base.prefill_base_s, base.prefill_per_token_s)
        return cls(
            prefill_base_s=p_int,
            prefill_per_token_s=p_slope,
            decode_base_s=d_int / T,
            decode_per_seq_s=d_slope / T,
            dispatch_overhead_s=0.0,  # folded into the decode intercept
            speed=speed,
        )

    @classmethod
    def fit_records(cls, records, speed: float = 1.0) -> "SimTiming":
        """Fit from flight-recorder `IterationRecord`s (runtime/
        flight_recorder.py dumps, `records` key) — the always-on black box
        every engine carries, so a production incident dump doubles as
        mocker calibration input. Accepts dataclasses or dicts.

        Decode iterations fit per-STEP (y = wall_s / decode_steps against
        x = decode_seqs) so dumps taken at different multi-step settings
        land on one model; prefill iterations fit y = wall_s against
        x = charged_tokens (what the dispatch was actually billed).
        `mixed` iterations are skipped — their wall time blends both
        regimes and would bias both fits."""

        def get(m, k, default=None):
            v = getattr(m, k, None) if not isinstance(m, dict) else m.get(k)
            return default if v is None else v

        from dynamo_tpu.planner.hw_profile import fit_line

        dec, pre = [], []
        for r in records:
            kind = get(r, "kind")
            wall = float(get(r, "wall_s", 0.0) or 0.0)
            if wall <= 0.0:
                continue
            if kind == "decode":
                steps = max(1, int(get(r, "decode_steps", 1) or 1))
                dec.append((int(get(r, "decode_seqs", 0) or 0),
                            wall / steps))
            elif kind == "prefill":
                toks = int(get(r, "charged_tokens", 0) or 0)
                if toks <= 0:
                    toks = sum(get(r, "chunk_tokens", []) or [])
                if toks > 0:
                    pre.append((toks, wall))
        base = cls()
        d_int, d_slope = fit_line(dec, base.decode_base_s,
                                  base.decode_per_seq_s)
        p_int, p_slope = fit_line(pre, base.prefill_base_s,
                                  base.prefill_per_token_s)
        return cls(
            prefill_base_s=p_int,
            prefill_per_token_s=p_slope,
            decode_base_s=d_int,
            decode_per_seq_s=d_slope,
            dispatch_overhead_s=0.0,  # folded into the decode intercept
            speed=speed,
        )

    def calibration_error(self, records) -> dict:
        """How well THIS timing model reproduces a set of
        `IterationRecord`s: per-kind MAPE plus the headline itl_p50_err —
        relative error between the median observed per-step decode time
        and the model's prediction at the median decode batch (the bound
        ISSUE/docs track: ≤ 15% means the twin's ITL distribution is
        trustworthy)."""

        def get(m, k, default=None):
            v = getattr(m, k, None) if not isinstance(m, dict) else m.get(k)
            return default if v is None else v

        dec_obs, dec_pred, pre_obs, pre_pred = [], [], [], []
        for r in records:
            kind = get(r, "kind")
            wall = float(get(r, "wall_s", 0.0) or 0.0)
            if wall <= 0.0:
                continue
            if kind == "decode":
                steps = max(1, int(get(r, "decode_steps", 1) or 1))
                n = int(get(r, "decode_seqs", 0) or 0)
                dec_obs.append(wall / steps)
                dec_pred.append(self.decode_base_s
                                + n * self.decode_per_seq_s)
            elif kind == "prefill":
                toks = int(get(r, "charged_tokens", 0) or 0)
                if toks <= 0:
                    toks = sum(get(r, "chunk_tokens", []) or [])
                if toks <= 0:
                    continue
                pre_obs.append(wall)
                pre_pred.append(self.prefill_base_s
                                + toks * self.prefill_per_token_s)

        def mape(obs, pred):
            pairs = [(o, p) for o, p in zip(obs, pred) if o > 0]
            if not pairs:
                return None
            return sum(abs(p - o) / o for o, p in pairs) / len(pairs)

        itl_err = None
        if dec_obs:
            obs_p50 = float(np.median(dec_obs))
            pred_p50 = float(np.median(dec_pred))
            if obs_p50 > 0:
                itl_err = abs(pred_p50 - obs_p50) / obs_p50
        return {
            "n_decode": len(dec_obs),
            "n_prefill": len(pre_obs),
            "decode_mape": mape(dec_obs, dec_pred),
            "prefill_mape": mape(pre_obs, pre_pred),
            "itl_p50_err": itl_err,
        }


def _sat_bucket(buckets, n: int) -> int:
    """Smallest bucket >= n, saturating at the largest (the mocker never
    fails a dispatch — an overflowing pack just pays the biggest shape)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


def _sim_token(seed: int, position: int, vocab: int = 50000) -> int:
    # deterministic, avoids special ids < 16
    return (seed * 1103515245 + position * 2654435761) % (vocab - 16) + 16


class SimRunner:
    """Drop-in for ModelRunner inside InferenceEngine (no JAX)."""

    # guided rows ride full multi-step loops: decode_multi honors the
    # engine's host-callback mask context between fused steps, so the
    # scheduler never collapses a constrained plan to n_steps=1
    guided_fused = True

    def __init__(
        self,
        *,
        num_pages: int = 2048,
        page_size: int = 16,
        max_pages_per_seq: int = 256,
        timing: Optional[SimTiming] = None,
        vocab_size: int = 50000,
        spec_accept_rate: Optional[float] = None,
        kv_export_bytes: bool = False,
    ):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.timing = timing or SimTiming()
        self.vocab_size = vocab_size
        # when set, export_pages emits tiny REAL KV arrays instead of the
        # hash-only marker, so G2/G3 offload writes actual files and the
        # disk tier's read/decode/quarantine machinery runs for real in
        # chaos sims (hash-only blocks never touch the filesystem)
        self.kv_export_bytes = kv_export_bytes
        # oracle drafting knob for spec-decode A/Bs: when set, spec_draft
        # proposes the TRUE sim stream corrupted per-token with
        # probability (1 - rate), so benches sweep acceptance without
        # changing the emitted bytes (verify always corrects mismatches).
        # None = no oracle; the engine falls back to n-gram proposal.
        self.spec_accept_rate = spec_accept_rate
        # dispatched-vs-charged token accounting for packed prefills, so
        # A/Bs can assert what the cost model billed (acceptance: ragged
        # mode bills sum(chunk_tokens), padded bills N_bucket x S_bucket)
        self.stats = {
            # real prompt tokens prefilled through ANY path (single-chunk,
            # packed, or verify-ridealong) — with tree reuse the scheduler
            # only dispatches the un-reused suffix, so this counter is the
            # honest "prefill work actually done" figure A/Bs difference
            "prefill_tokens_real": 0,
            "packed_dispatches": 0,
            "packed_tokens_real": 0,
            "packed_tokens_charged": 0,
            "spec_dispatches": 0,
            "spec_tokens_charged": 0,
            # device draft ring: fused append+propose dispatches billed
            # (engine._device_draft issues at most one per iteration)
            "draft_dispatches": 0,
            "onboards_streamed": 0,
            "onboard_overlap_s": 0.0,
            "page_copies": 0,
        }
        # wall-clock instant the deepest in-flight layer group of a
        # streamed onboard lands (0.0 = nothing in flight). Dispatches
        # that consume onboarded pages block on it before returning.
        self._onboard_ready_t = 0.0
        self._onboard_rest_s = 0.0

    # -- ModelRunner interface ---------------------------------------------
    def prefill(self, tokens: List[int], start_pos: int, page_table_row, prior_len: int, adapter: int = 0, mm=None):
        t = self.timing
        self.stats["prefill_tokens_real"] += len(tokens)
        t.sleep(t.prefill_base_s + len(tokens) * t.prefill_per_token_s)
        self._drain_onboard()
        # "logits": seeded by the LAST prompt token + position only, so the
        # first sampled token is identical whether the prefix came from
        # cache or was recomputed (chunk-invariant); subsequent decode
        # tokens chain deterministically off the fed token
        seed = tokens[-1] if tokens else 0
        return ("sim-logits", seed, start_pos + len(tokens))

    def prefill_packed(self, chunks):
        """Token-budget packed prefill: the whole chunk set rides ONE
        simulated dispatch, so the step-time model charges the dispatch
        base once plus the per-token cost of every packed token — the
        timing shape of the runner's fused ragged program. Takes the
        engine's chunk dicts ({"tokens", "start", ...}); returns one
        sim-logits tuple per chunk."""
        t = self.timing
        total = sum(len(c["tokens"]) for c in chunks)
        charged = t.packed_charge_tokens([len(c["tokens"]) for c in chunks])
        self.stats["packed_dispatches"] += 1
        self.stats["prefill_tokens_real"] += total
        self.stats["packed_tokens_real"] += total
        self.stats["packed_tokens_charged"] += charged
        t.sleep(t.prefill_base_s + charged * t.prefill_per_token_s)
        self._drain_onboard()
        out = []
        for c in chunks:
            toks = c["tokens"]
            seed = toks[-1] if toks else 0
            out.append(("sim-logits", seed, c["start"] + len(toks)))
        return out

    def sample_one(self, logits, sampling, step: int, mask=None) -> int:
        _, seed, position = logits
        tok = _sim_token(seed, position, self.vocab_size)
        if mask is not None and not mask[tok]:
            # guided decoding against the mocker: honor the mask by
            # remapping onto the allowed set (deterministic in the seed);
            # an empty mask passes through (engine force-stops it)
            allowed = np.flatnonzero(mask)
            if len(allowed):
                tok = int(allowed[tok % len(allowed)])
        return tok

    def decode_multi(
        self, n_steps: int, tokens: List[int], positions: List[int],
        page_tables, sampling, step: int, adapters=None, masks=None,
        mask_fn=None, guided_dev=None,
    ) -> np.ndarray:
        t = self.timing
        t.sleep(
            t.dispatch_overhead_s
            + n_steps * (t.decode_base_s + len(tokens) * t.decode_per_seq_s)
        )
        self._drain_onboard()
        # device-resident guided plan: the numpy twin of the runner's
        # in-XLA DFA walk (_decode_loop's `guided` operand) — combined
        # transition/mask tables, per-row global states, advance-before-
        # mask on every step after the first. Byte-identity between this
        # and the mask_fn callback path is what pins the device tables
        # as a pure transport change (tests/test_guided.py).
        gtrans = gmask = gstate = None
        gpend = False
        if guided_dev is not None:
            from dynamo_tpu.guided.device_table import combine_tables

            g_tables, g_rows, gpend = guided_dev
            gtrans, gmask, offs = combine_tables(g_tables)
            gstate = np.full(len(tokens), gtrans.shape[0] - 1, np.int64)
            for i, ent in enumerate(g_rows):
                if ent is not None:
                    ti, st = ent
                    gstate[i] = offs[ti] + int(st)
        # step-outer: each fused step is seeded by the PREVIOUS EMITTED
        # token (like the real on-device feedback loop, where the masked
        # sample is what gets fed back), so the sim stream is a pure
        # function of (prev_emitted_token, position) and is invariant to
        # dispatch boundaries — the property spec-decode and guided
        # byte-identity A/Bs assert. For unguided rows emitted == raw,
        # so this matches the legacy raw-chained stream exactly.
        out = np.zeros((len(tokens), n_steps), np.int32)
        prev = list(tokens)
        for j in range(n_steps):
            if gtrans is not None:
                if j > 0 or gpend:
                    gstate = gtrans[gstate, prev]
                m = gmask[gstate]
            elif mask_fn is not None:
                # the engine's host-callback mask context: advances the
                # per-row DFA state off the step's emitted tokens, same
                # contract the real runner's io_callback uses
                m = np.asarray(mask_fn(j, np.asarray(prev, np.int32)))
            elif masks is not None and j == 0:
                m = masks
            else:
                m = None
            for i in range(len(tokens)):
                tok = _sim_token(prev[i], positions[i] + 1 + j, self.vocab_size)
                if m is not None and not m[i, tok]:
                    allowed = np.flatnonzero(m[i])
                    if len(allowed):
                        tok = int(allowed[tok % len(allowed)])
                out[i, j] = tok
                prev[i] = tok
        return out

    # -- speculative decoding (n-gram / oracle drafting) --------------------
    def spec_draft(self, last_token: int, pos: int, k: int):
        """Oracle draft source for A/Bs: proposes the true chained sim
        stream, corrupting each position independently with probability
        (1 - spec_accept_rate), deterministic in (token, position).
        Returns None when the knob is unset — the engine then uses
        n-gram proposal like on a real runner."""
        rate = self.spec_accept_rate
        if rate is None:
            return None
        drafts: List[int] = []
        prev = last_token
        for j in range(k):
            true = _sim_token(prev, pos + 1 + j, self.vocab_size)
            u = _sim_token(prev ^ 0x5BD1E99, pos + 1 + j, self.vocab_size)
            if (u % 10000) / 10000.0 < rate:
                drafts.append(true)
            else:
                # corrupted draft: a different valid token id (stays >= 16)
                drafts.append((true - 16 + 1) % (self.vocab_size - 16) + 16)
            prev = true  # the oracle keeps proposing along the true stream
        return drafts

    def spec_draft_tree(self, last_token: int, pos: int, k: int,
                        branches: int):
        """Tree-draft oracle: branch 0 is exactly spec_draft's proposal;
        extra branches follow the same true stream with an INDEPENDENT
        corruption pattern at the same per-position accept rate. At equal
        per-branch acceptance, the union of branches accepts strictly
        more prefix than any single branch — the effect tree speculation
        spends its forked verify rows to buy, which is what
        `bench_spec.py --tree` A/Bs measure. Returns None when the
        oracle knob is unset (the engine then uses host tree proposal)."""
        rate = self.spec_accept_rate
        if rate is None:
            return None
        out = [self.spec_draft(last_token, pos, k)]
        for b in range(1, max(1, branches)):
            drafts: List[int] = []
            prev = last_token
            for j in range(k):
                true = _sim_token(prev, pos + 1 + j, self.vocab_size)
                u = _sim_token(
                    (prev ^ 0x5BD1E99) + 7919 * b, pos + 1 + j,
                    self.vocab_size,
                )
                if (u % 10000) / 10000.0 < rate:
                    drafts.append(true)
                else:
                    drafts.append(
                        (true - 16 + 1 + b) % (self.vocab_size - 16) + 16
                    )
                prev = true
            out.append(drafts)
        return out

    # -- device n-gram draft ring (numpy twin of ModelRunner's jitted
    # ring; see model_runner._draft_ring_step) ------------------------------
    def ensure_draft_ring(self, slots: int, k: int, window: int = 512) -> int:
        self._draft_hist: List[List[int]] = [[] for _ in range(int(slots))]
        self._draft_window = int(window)
        return max(16, int(k) + 2)

    def draft_ring_reset(self, slot: int, tokens: List[int]) -> None:
        self._draft_hist[slot] = [int(x) for x in tokens][-self._draft_window:]

    def draft_step(self, updates, k: int):
        """Numpy twin of the fused device proposal: append each (slot,
        delta), then propose per slot with the SAME suffix-match
        semantics as the host scan bounded to the ring window. Billed as
        ONE dispatch regardless of batch — the cost shape that makes
        device drafting worth A/B-ing against the per-sequence scan."""
        from dynamo_tpu.engine.ngram_draft import propose

        t = self.timing
        self.stats["draft_dispatches"] += 1
        t.sleep(t.draft_propose_s)
        W = self._draft_window
        for slot, delta in updates:
            h = self._draft_hist[slot]
            h.extend(int(x) for x in delta)
            if len(h) > W:
                del h[: len(h) - W]
        slots = len(self._draft_hist)
        drafts = np.full((slots, max(1, int(k))), -1, np.int32)
        n_prop = np.zeros(slots, np.int32)
        for s, h in enumerate(self._draft_hist):
            d = propose(h, int(k), window=W)
            n_prop[s] = len(d)
            if d:
                drafts[s, : len(d)] = d
        return drafts, n_prop

    def verify_spec(
        self, tokens: List[int], positions: List[int], page_tables,
        drafts: List[List[int]], sampling, step: int, chunks=(),
        masks=None,
    ):
        """Speculative verify as ONE simulated ragged flat-token dispatch:
        row i contributes len(drafts[i])+1 verify positions (a plain
        decode row when the draft is empty). Returns (rows, chunk_logits)
        where rows[i][j] is the target-sampled token at verify position j
        — the token the target model emits after feeding the row's last
        real token (j=0) or drafts[i][j-1] (j>0).

        Billing: one dispatch paying the decode sweep for every row plus
        the per-token verify compute, charged drafted+1 tokens per
        speculating row under prefill_cost="ragged" (bucket-padded under
        "padded"). Charges land in packed_tokens_charged so the flight
        recorder's per-iteration charged-token delta stays honest."""
        t = self.timing
        spec_lens = [len(d) for d in drafts]
        charged = t.spec_charge_tokens(spec_lens)
        chunk_charged = 0
        if chunks:
            chunk_charged = t.packed_charge_tokens(
                [len(c["tokens"]) for c in chunks]
            )
            real = sum(len(c["tokens"]) for c in chunks)
            self.stats["prefill_tokens_real"] += real
            self.stats["packed_tokens_real"] += real
        self.stats["spec_dispatches"] += 1
        self.stats["spec_tokens_charged"] += charged
        self.stats["packed_dispatches"] += 1
        self.stats["packed_tokens_charged"] += charged + chunk_charged
        t.sleep(
            t.dispatch_overhead_s
            + t.decode_base_s
            + len(tokens) * t.decode_per_seq_s
            + (charged + chunk_charged) * t.prefill_per_token_s
        )
        self._drain_onboard()
        rows = []
        for ri, (tok, pos, d) in enumerate(zip(tokens, positions, drafts)):
            out = np.zeros(len(d) + 1, np.int32)
            m = masks.get(ri) if masks else None
            for j in range(len(d) + 1):
                fed = tok if j == 0 else d[j - 1]
                out[j] = _sim_token(fed, pos + 1 + j, self.vocab_size)
                if m is not None and not m[out[j]]:
                    # guided rows ride verify draft-less (one position);
                    # honor the mask with the same deterministic remap
                    # sample_one / decode_multi use
                    allowed = np.flatnonzero(m)
                    if len(allowed):
                        out[j] = int(allowed[out[j] % len(allowed)])
            rows.append(out)
        chunk_logits = []
        for c in chunks:
            toks = c["tokens"]
            seed = toks[-1] if toks else 0
            chunk_logits.append(("sim-logits", seed, c["start"] + len(toks)))
        return rows, chunk_logits

    def decode(self, tokens, positions, page_tables, kv_lens, sampling, step):
        return self.decode_multi(1, tokens, positions, page_tables, sampling, step)[:, 0]

    def copy_pages(self, src: int, dst: int) -> None:
        """Fork-on-branch CoW page duplication — pure billing in the sim
        (there is no KV payload), but the cost model charges the device
        DMA so fork A/Bs don't measure a fictional free copy."""
        self.timing.sleep(self.timing.page_copy_s)
        self.stats["page_copies"] += 1

    def embed(self, token_lists: List[List[int]]) -> np.ndarray:
        self.timing.sleep(self.timing.prefill_base_s)
        out = np.zeros((len(token_lists), 16), np.float32)
        for i, t in enumerate(token_lists):
            rng = np.random.default_rng(sum(t) % (2**31))
            v = rng.standard_normal(16)
            out[i] = v / np.linalg.norm(v)
        return out

    # -- disagg KV transfer (simulated) ------------------------------------
    def export_pages(self, pages: List[int]):
        if not self.kv_export_bytes:
            return {"data": True, "sim": True, "n_pages": len(pages)}
        from dynamo_tpu.engine.model_runner import kv_arrays_to_payload

        # deterministic per-page planes, [L=1, n, PS, Hk=1, D=4] — small
        # enough that a 500-worker sim's spills stay cheap, real enough
        # that encode/decode_block round-trips (and corruption trips the
        # quarantine) exactly as on a real engine
        k = np.stack([
            np.full((1, self.page_size, 1, 4), float(p), dtype=np.float32)
            for p in pages
        ], axis=1)
        return kv_arrays_to_payload(k, k + 0.5)

    def import_pages(self, target_pages, offset: int, payload,
                     layer_groups: int = 1) -> None:
        # the transfer isn't free: charge the step-time model so KVBM
        # onboarding (sync or prefetched) costs simulated wall time
        t = self.timing
        dma = len(target_pages) * t.onboard_per_page_s
        g = max(1, int(layer_groups))
        if g == 1 or t.speed <= 0:
            t.sleep(t.onboard_base_s + dma)
            return
        # layer-streamed: block only for the first group (shallow layers
        # must be resident before prefill issues); the remaining groups
        # keep streaming while later compute runs. Their landing time is
        # recorded as a wall-clock deadline that the NEXT consuming
        # dispatch waits out — overlapped transfer is hidden only to the
        # extent real compute covers it, never dropped. Each extra group
        # pays its own issue setup (onboard_group_base_s), so very large
        # G values are honestly counter-productive.
        self.stats["onboards_streamed"] += 1
        t.sleep(t.onboard_base_s + dma / g)
        rest = dma * (g - 1) / g + (g - 1) * t.onboard_group_base_s
        self._onboard_ready_t = max(
            self._onboard_ready_t, time.monotonic() + rest * t.speed
        )
        self._onboard_rest_s = rest * t.speed

    def _drain_onboard(self) -> None:
        """Block until in-flight streamed layer groups have landed. Called
        at the tail of every consuming dispatch: the dispatch's own compute
        already advanced the clock, so only the uncovered remainder (if
        any) is slept — that remainder is exactly the non-overlapped part
        of the transfer."""
        if self._onboard_ready_t <= 0.0:
            return
        rem = self._onboard_ready_t - time.monotonic()
        self._onboard_ready_t = 0.0
        hidden = self._onboard_rest_s - max(0.0, rem)
        if hidden > 0:
            self.stats["onboard_overlap_s"] += hidden
        self._onboard_rest_s = 0.0
        if rem > 0:
            time.sleep(rem)
