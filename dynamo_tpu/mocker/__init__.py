"""Simulation stack (analog of reference lib/mocker + dynamo.mocker):
GPU/TPU-free engines with real registration, KV events, and timing models.
"""
