"""Calibrated, chaos-instrumented fleet simulator (the digital twin).

One process hosts hundreds of mocker workers — real scheduler, real page
pool, real KV events, fake accelerator — on the in-proc request plane
(`runtime/request_plane.py` `inproc://`), behind the real frontend stack
(ModelWatcher → Migration → router). `SimTiming` can be calibrated from
flight-recorder dumps (`SimTiming.fit_records`), traffic comes from the
scenario matrix (bench/loadgen.py: agentic/rag/json/burst), and a
`FaultSchedule` injects the failures production will eventually serve up:

  kill         SIGKILL a worker mid-stream (endpoint aborted, digests
               silenced, discovery unregistered — clients see the
               migratable `disconnected`, the indexer sees the delete)
  restart      bring a fresh worker up in a killed worker's slot
  partition    request-plane connect/send/recv raise ConnectionResetError
               for a window (per worker or fleet-wide)
  delay        request-plane edges sleep `param` seconds for a window
  corrupt_kv   garble on-disk KV tier blocks (disk_pool quarantine path)
  digest_drop  the worker's fleet digests are silently dropped
  digest_dup   every digest is published twice (observer seq dedup path)

Schedule grammar (`FaultSchedule.parse`): events joined by `;`, each

  kind@START[+DURATION][:wIDX|w*][=PARAM]

  kill@10:w3                 kill worker 3 at t=10s (trace clock)
  partition@20+5:w1          cut worker 1's request plane for 5s
  delay@30+10:w*=0.05        50ms added to every plane edge for 10s
  corrupt_kv@40:w2=4         garble 4 disk-tier blocks of worker 2
  digest_drop@50+20:w4       worker 4 goes digest-silent for 20s
  restart@60:w3              new worker in slot 3

`FleetSim.run()` reports router p50 decision time, migration success
rate, SLO attainment (goodput + SLO-engine state), and fault counts.
The whole run is seeded: same seed + same schedule → same token streams,
same winners, same report shape.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dynamo_tpu.bench.loadgen import (
    aggregate_migration,
    aggregate_phases,
    compute_goodput,
    compute_scenario_matrix,
    generate_scenarios,
    run_sessions_against_engine,
)
from dynamo_tpu.frontend.service import ModelManager, ModelWatcher
from dynamo_tpu.mocker.__main__ import build_mock_engine
from dynamo_tpu.mocker.__main__ import parse_args as mocker_args
from dynamo_tpu.runtime import request_plane as rp
from dynamo_tpu.runtime.discovery import MemDiscovery
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.event_plane import FLEET_DIGEST_SUBJECT

log = logging.getLogger("dynamo_tpu.fleet_sim")

_EVENT_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<at>[0-9.]+)"
    r"(?:\+(?P<dur>[0-9.]+))?"
    r"(?::w(?P<worker>\d+|\*))?"
    r"(?:=(?P<param>[0-9.]+))?$"
)

FAULT_KINDS = ("kill", "restart", "partition", "delay", "corrupt_kv",
               "digest_drop", "digest_dup")


@dataclass
class FaultEvent:
    kind: str
    at_s: float  # trace-clock offset into the run
    duration_s: float = 0.0  # windowed faults; 0 = instantaneous
    worker: Optional[int] = None  # worker slot index; None = fleet-wide
    param: float = 0.0  # kind-specific (delay seconds, corrupt count)

    def to_text(self) -> str:
        s = f"{self.kind}@{self.at_s:g}"
        if self.duration_s:
            s += f"+{self.duration_s:g}"
        s += ":w*" if self.worker is None else f":w{self.worker}"
        if self.param:
            s += f"={self.param:g}"
        return s


class FaultSchedule:
    def __init__(self, events: List[FaultEvent]):
        self.events = sorted(events, key=lambda e: e.at_s)

    def __len__(self) -> int:
        return len(self.events)

    def to_text(self) -> str:
        return ";".join(e.to_text() for e in self.events)

    @classmethod
    def parse(cls, text: str) -> "FaultSchedule":
        events = []
        for raw in text.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            m = _EVENT_RE.match(raw)
            if m is None:
                raise ValueError(f"bad fault event {raw!r} "
                                 "(kind@start[+dur][:wIDX|w*][=param])")
            kind = m.group("kind")
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (have {FAULT_KINDS})")
            w = m.group("worker")
            events.append(FaultEvent(
                kind=kind,
                at_s=float(m.group("at")),
                duration_s=float(m.group("dur") or 0.0),
                worker=None if w in (None, "*") else int(w),
                param=float(m.group("param") or 0.0),
            ))
        return cls(events)

    @classmethod
    def generate(
        cls,
        seed: int,
        n_workers: int,
        duration_s: float,
        kills_per_min: float = 1.0,
        restart_after_s: float = 20.0,
        partitions_per_min: float = 0.5,
        partition_s: float = 5.0,
        digest_faults_per_min: float = 0.5,
        digest_fault_s: float = 15.0,
    ) -> "FaultSchedule":
        """The worker-death day: Poisson kill arrivals, each followed by a
        restart into the same slot, plus partition and digest-loss
        windows. Deterministic per seed."""
        rng = random.Random(seed)
        events: List[FaultEvent] = []

        def arrivals(rate_per_min: float):
            t = 0.0
            while rate_per_min > 0:
                t += rng.expovariate(rate_per_min / 60.0)
                if t >= duration_s:
                    return
                yield t

        for t in arrivals(kills_per_min):
            w = rng.randrange(n_workers)
            events.append(FaultEvent("kill", t, worker=w))
            if t + restart_after_s < duration_s:
                events.append(
                    FaultEvent("restart", t + restart_after_s, worker=w))
        for t in arrivals(partitions_per_min):
            events.append(FaultEvent(
                "partition", t, duration_s=partition_s,
                worker=rng.randrange(n_workers)))
        for t in arrivals(digest_faults_per_min):
            kind = rng.choice(("digest_drop", "digest_dup"))
            events.append(FaultEvent(
                kind, t, duration_s=digest_fault_s,
                worker=rng.randrange(n_workers)))
        return cls(events)


@dataclass
class SimWorker:
    idx: int
    runtime: DistributedRuntime
    served: Any  # ServedWorker
    engine: Any
    alive: bool = True
    disk_root: Optional[str] = None
    digest_state: Dict[str, float] = field(default_factory=dict)


class _FaultyDigestPublisher:
    """EventPublisher proxy in front of a worker's digest publishes:
    drops or duplicates FLEET_DIGEST_SUBJECT payloads per the fault
    windows in `state` ({"drop_until": t, "dup_until": t}, loop clock).
    Everything else passes through untouched."""

    def __init__(self, pub, state: Dict[str, float]):
        self._pub = pub
        self._state = state

    @property
    def address(self) -> str:
        return self._pub.address

    async def publish(self, subject: str, payload: Any) -> None:
        if subject == FLEET_DIGEST_SUBJECT:
            now = asyncio.get_event_loop().time()
            if now < self._state.get("drop_until", 0.0):
                return
            await self._pub.publish(subject, payload)
            if now < self._state.get("dup_until", 0.0):
                await self._pub.publish(subject, payload)
            return
        await self._pub.publish(subject, payload)


class FleetSim:
    """N mocker workers + real frontend stack in one process, with the
    fault-injection plane wired through the in-proc transport."""

    def __init__(
        self,
        n_workers: int,
        router_mode: str = "kv",
        seed: int = 0,
        speed: float = 0.02,  # SimTiming scale (0 = no sleeps)
        decode_base_ms: float = 4.0,
        idle_sleep_s: float = 0.05,  # engine-thread idle poll (see below)
        num_pages: int = 128,
        page_size: int = 16,
        max_batch: int = 16,
        timing=None,  # calibrated SimTiming override (fit_records)
        digest_period_s: float = 1.0,
        digest_window_s: float = 5.0,
        slo: str = "ttft:p99<2.0,itl:p50<0.05",
        migration_limit: int = 3,
        migration_backoff_base_s: float = 0.02,
        sick_cooldown_s: float = 2.0,
        session_affinity_ttl: Optional[float] = None,
        host_kv_blocks: int = 0,  # G2 tier; auto-enabled by disk_kv_blocks
        disk_kv_blocks: int = 0,
        disk_kv_base: Optional[str] = None,  # per-worker roots under here
        sanitize: bool = True,  # fleet-sim default harness: one shared
        #   non-strict Sanitizer across all workers; run() reports its
        #   block and chaos tests assert zero violations
    ):
        self.n_workers = n_workers
        self.router_mode = router_mode
        self.seed = seed
        self.speed = speed
        self.decode_base_ms = decode_base_ms
        # hundreds of engine step threads in one process: a 2ms idle poll
        # x 500 threads is 250k wakeups/s of pure GIL churn — widen it
        self.idle_sleep_s = idle_sleep_s
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_batch = max_batch
        self.timing = timing
        self.digest_period_s = digest_period_s
        self.digest_window_s = digest_window_s
        self.slo = slo
        self.migration_limit = migration_limit
        self.migration_backoff_base_s = migration_backoff_base_s
        self.sick_cooldown_s = sick_cooldown_s
        self.session_affinity_ttl = session_affinity_ttl
        # the disk tier spills from the host tier: G3 implies G2
        if disk_kv_blocks > 0 and host_kv_blocks <= 0:
            host_kv_blocks = max(8, disk_kv_blocks // 2)
        self.host_kv_blocks = host_kv_blocks
        self.disk_kv_blocks = disk_kv_blocks
        self.disk_kv_base = disk_kv_base

        self.realm = f"fleet-{seed}-{os.getpid()}-{id(self):x}"
        self.workers: List[SimWorker] = []
        self.frontend_runtime: Optional[DistributedRuntime] = None
        self.manager: Optional[ModelManager] = None
        self.watcher: Optional[ModelWatcher] = None
        self.observer = None
        self.slo_engine = None
        self._digest_watch: Optional[asyncio.Task] = None
        self._addr_to_idx: Dict[str, int] = {}
        # fault state consulted by the in-proc fault hook; keys are worker
        # slot indices or "*" (fleet-wide), values are loop-clock deadlines
        self._partitions: Dict[Any, float] = {}
        self._delays: Dict[Any, tuple] = {}  # key -> (until, seconds)
        self.fault_counts: Dict[str, int] = {}
        self.sanitizer = None
        if sanitize:
            from dynamo_tpu.runtime.sanitizer import Sanitizer

            # non-strict: chaos faults must play out and the report show
            # every violation, not die on the first
            self.sanitizer = Sanitizer(strict=False)

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        rp.set_inproc_fault_hook(self._fault_hook)
        if self.sanitizer is not None:
            self.sanitizer.start_watchdog()
        for i in range(self.n_workers):
            await self._spawn_worker(i)
        frt = DistributedRuntime(
            discovery=MemDiscovery(realm=self.realm),
            event_transport="inproc", request_plane="inproc",
        )
        self.frontend_runtime = frt
        self.manager = ModelManager()
        self.watcher = ModelWatcher(
            frt, self.manager, router_mode=self.router_mode,
            migration_limit=self.migration_limit,
            session_affinity_ttl=self.session_affinity_ttl,
        )
        await self.watcher.start()
        await self.watcher.wait_for_model(timeout=30)
        from dynamo_tpu.frontend.migration import Migration
        from dynamo_tpu.planner.slo import SloEngine, parse_slo_config
        from dynamo_tpu.runtime.fleet_observer import FleetObserver

        # the chaos schedule compresses days into seconds — scale the
        # retry backoff and failure-cache TTL with it
        for entry in self.manager.models.values():
            stage = (entry.chain.get("migration")
                     if hasattr(entry.chain, "get") else None)
            if isinstance(stage, Migration):
                stage.backoff_base_s = self.migration_backoff_base_s
            client = getattr(entry, "client", None)
            router = getattr(client, "router", None)
            if router is not None:
                router.sick_cooldown_s = self.sick_cooldown_s
        self.observer = FleetObserver(
            frt.event_subscriber([FLEET_DIGEST_SUBJECT]),
            window_s=self.digest_window_s,
        )
        await self.observer.start()
        self.slo_engine = SloEngine(self.observer, parse_slo_config(self.slo))

        async def _watch_digests():
            try:
                async for ev in frt.discovery.watch("services/"):
                    addr = (ev.instance.metadata or {}).get("digest_publisher")
                    if ev.kind == "put" and addr:
                        self.observer.connect_publisher(addr)
            except asyncio.CancelledError:
                pass

        self._digest_watch = asyncio.get_running_loop().create_task(
            _watch_digests())

    async def _spawn_worker(self, idx: int) -> SimWorker:
        from dynamo_tpu.worker_common import serve_worker

        rt = DistributedRuntime(
            discovery=MemDiscovery(realm=self.realm),
            event_transport="inproc", request_plane="inproc",
        )
        flags = [
            "--speed", str(self.speed),
            "--decode-base-ms", str(self.decode_base_ms),
            "--page-size", str(self.page_size),
            "--num-pages", str(self.num_pages),
            "--max-batch", str(self.max_batch),
        ]
        if self.host_kv_blocks > 0:
            flags += ["--host-kv-blocks", str(self.host_kv_blocks)]
        disk_root = None
        if self.disk_kv_blocks > 0:
            base = self.disk_kv_base or "/tmp/fleet_sim_kv"
            disk_root = os.path.join(base, self.realm, f"w{idx}")
            os.makedirs(disk_root, exist_ok=True)
            # real (tiny) KV bytes so the disk tier writes actual files —
            # corrupt_kv garbles them and the quarantine path runs for real
            flags += ["--disk-kv-blocks", str(self.disk_kv_blocks),
                      "--disk-kv-root", disk_root, "--kv-export-bytes"]
        margs = mocker_args(flags)
        engine, card = build_mock_engine(
            margs, timing=self.timing, idle_sleep_s=self.idle_sleep_s,
            sanitizer=self.sanitizer)
        digest_state: Dict[str, float] = {}
        served = await serve_worker(
            rt, engine, card, digest_period_s=self.digest_period_s)
        if served.digest_pub is not None:
            served.digest_pub.pub = _FaultyDigestPublisher(
                served.digest_pub.pub, digest_state)
        w = SimWorker(idx=idx, runtime=rt, served=served, engine=engine,
                      disk_root=disk_root, digest_state=digest_state)
        if idx < len(self.workers):
            self.workers[idx] = w
        else:
            self.workers.append(w)
        self._addr_to_idx[rt.server.address] = idx
        return w

    async def stop(self) -> None:
        if self._digest_watch is not None:
            self._digest_watch.cancel()
        if self.observer is not None:
            await self.observer.stop()
        if self.watcher is not None:
            await self.watcher.stop()
        if self.frontend_runtime is not None:
            await self.frontend_runtime.shutdown(drain_timeout=1)
        for w in self.workers:
            if w.alive:
                try:
                    await w.served.stop()
                    await w.runtime.shutdown(drain_timeout=1)
                except Exception:
                    log.debug("worker %d teardown failed", w.idx,
                              exc_info=True)
        if self.sanitizer is not None:
            await self.sanitizer.stop_watchdog()
            self.sanitizer.audit_tasks()
        rp.set_inproc_fault_hook(None)

    # -- fault plane -------------------------------------------------------
    async def _fault_hook(self, direction: str, address: str) -> None:
        idx = self._addr_to_idx.get(address)
        now = asyncio.get_event_loop().time()
        for key in (idx, "*"):
            if key is None:
                continue
            d = self._delays.get(key)
            if d is not None and now < d[0]:
                await asyncio.sleep(d[1])
            p = self._partitions.get(key)
            if p is not None and now < p:
                raise ConnectionResetError(f"partitioned: {address}")

    def _count(self, kind: str) -> None:
        self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1

    async def kill_worker(self, idx: int) -> None:
        """SIGKILL twin: the endpoint vanishes mid-frame (clients see
        `disconnected`), digests go silent WITHOUT a flush, discovery gets
        the delete (indexer expiry + router instance removal), and the
        engine thread is joined. No goodbyes anywhere."""
        w = self.workers[idx]
        if not w.alive:
            return
        w.alive = False
        self._count("kill")
        w.runtime.server.abort()
        dp = w.served.digest_pub
        if dp is not None:
            if dp._task is not None:
                dp._task.cancel()
                dp._task = None

            async def _silent() -> None:
                return None

            dp.publish_once = _silent  # teardown must not flush a corpse
        if w.runtime._hb_task is not None:
            w.runtime._hb_task.cancel()
        for inst in list(w.runtime._served):
            try:
                await w.runtime.discovery.unregister(inst)
            except Exception:
                log.debug("unregister during kill failed", exc_info=True)
        w.runtime._served.clear()
        w.engine.stop()

    async def restart_worker(self, idx: int) -> None:
        w = self.workers[idx]
        if w.alive:
            return
        self._count("restart")
        self._addr_to_idx.pop(w.runtime.server.address, None)
        await self._spawn_worker(idx)

    def partition(self, idx: Optional[int], duration_s: float) -> None:
        self._count("partition")
        key = "*" if idx is None else idx
        self._partitions[key] = (
            asyncio.get_event_loop().time() + duration_s)

    def delay(self, idx: Optional[int], duration_s: float,
              delay_s: float) -> None:
        self._count("delay")
        key = "*" if idx is None else idx
        self._delays[key] = (
            asyncio.get_event_loop().time() + duration_s, delay_s)

    def corrupt_kv(self, idx: int, n_blocks: int = 4) -> int:
        """Garble on-disk KV tier blocks of worker `idx`. disk_pool's
        quarantine must treat each as a miss (unlink + recompute), never
        raise into the onboard path."""
        w = self.workers[idx]
        self._count("corrupt_kv")
        if not w.disk_root or not os.path.isdir(w.disk_root):
            return 0
        files = []
        for dirpath, _, names in os.walk(w.disk_root):
            files.extend(os.path.join(dirpath, f) for f in names)
        files.sort()
        rng = random.Random(self.seed ^ (idx << 8) ^ len(files))
        rng.shuffle(files)
        corrupted = 0
        for path in files[:n_blocks]:
            try:
                with open(path, "r+b") as f:
                    f.truncate(max(1, os.path.getsize(path) // 3))
                corrupted += 1
            except OSError:
                continue
        return corrupted

    def digest_fault(self, idx: int, kind: str, duration_s: float) -> None:
        self._count(kind)
        key = "drop_until" if kind == "digest_drop" else "dup_until"
        w = self.workers[idx]
        w.digest_state[key] = asyncio.get_event_loop().time() + duration_s

    async def apply_event(self, ev: FaultEvent, time_scale: float = 1.0,
                          rng: Optional[random.Random] = None) -> None:
        idx = ev.worker
        if idx is None and ev.kind in ("kill", "restart", "corrupt_kv",
                                       "digest_drop", "digest_dup"):
            idx = (rng or random.Random(self.seed)).randrange(
                len(self.workers))
        dur = ev.duration_s * time_scale
        if ev.kind == "kill":
            await self.kill_worker(idx)
        elif ev.kind == "restart":
            await self.restart_worker(idx)
        elif ev.kind == "partition":
            self.partition(ev.worker, dur)
        elif ev.kind == "delay":
            self.delay(ev.worker, dur, ev.param)
        elif ev.kind == "corrupt_kv":
            # disk truncation walks + rewrites tier files: off the loop,
            # which carries every in-flight stream of the sim (DYN-A002)
            await asyncio.to_thread(self.corrupt_kv, idx, int(ev.param) or 4)
        elif ev.kind in ("digest_drop", "digest_dup"):
            self.digest_fault(idx, ev.kind, dur)

    async def _fault_pump(self, schedule: FaultSchedule, t0: float,
                          time_scale: float) -> None:
        rng = random.Random(self.seed ^ 0x5EED)
        loop = asyncio.get_event_loop()
        try:
            for ev in schedule.events:
                delay = ev.at_s * time_scale - (loop.time() - t0)
                if delay > 0:
                    await asyncio.sleep(delay)
                await self.apply_event(ev, time_scale, rng)
        except asyncio.CancelledError:
            pass

    # -- views -------------------------------------------------------------
    def alive_workers(self) -> int:
        return sum(1 for w in self.workers if w.alive)

    def active_streams(self) -> int:
        """In-flight server-side requests across live workers — must be 0
        after a drained run (the zero-hung-streams assertion)."""
        return sum(len(w.runtime.server._active)
                   for w in self.workers if w.alive)

    @property
    def entry(self):
        return self.manager.get("mock-model")

    # -- the experiment ----------------------------------------------------
    async def run(
        self,
        scenarios=("agentic", "rag", "json", "burst"),
        n_sessions: int = 8,
        rps: float = 4.0,
        time_scale: float = 1.0,
        fault_schedule: Optional[FaultSchedule] = None,
        ttft_slo_s: float = 2.0,
        itl_slo_s: float = 0.05,
    ) -> Dict[str, Any]:
        """Drive the scenario matrix through the frontend chain while the
        fault pump walks the schedule; returns the twin's report."""
        scripts = generate_scenarios(
            list(scenarios), n_sessions, rps=rps, seed=self.seed)
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        pump = None
        if fault_schedule is not None and len(fault_schedule):
            pump = loop.create_task(
                self._fault_pump(fault_schedule, t0, time_scale))
        try:
            results, duration = await run_sessions_against_engine(
                scripts, self.entry.chain.generate,
                time_scale=time_scale, seed=self.seed,
            )
        finally:
            if pump is not None:
                pump.cancel()
        report = compute_goodput(results, duration, ttft_slo_s, itl_slo_s)
        phases = aggregate_phases(results)
        route = phases.get("route_s") or {}
        mig = aggregate_migration(results)
        slo_view = self.slo_engine.evaluate() if self.slo_engine else {}
        out = {
            "workers": self.n_workers,
            "workers_alive": self.alive_workers(),
            "requests": len(results),
            "duration_s": round(duration, 3),
            "simulated_duration_s": round(
                duration / max(time_scale, 1e-9), 1),
            "rps": round(len(results) / max(duration, 1e-9), 2),
            "router_p50_decision_us": round(
                route.get("p50_s", 0.0) * 1e6, 1),
            "router_p95_decision_us": round(
                route.get("p95_s", 0.0) * 1e6, 1),
            "migration": mig,
            "migration_success_rate": mig.get("success_rate"),
            "slo_attainment": (report.n_slo_met / report.n_ok
                               if report.n_ok else 0.0),
            "slo_state": slo_view.get("state"),
            "goodput": json.loads(report.to_json()),
            "scenarios": compute_scenario_matrix(
                results, duration, ttft_slo_s, itl_slo_s),
            "faults": dict(self.fault_counts),
            "active_streams_after": self.active_streams(),
        }
        if self.sanitizer is not None:
            out["sanitizer"] = self.sanitizer.report()
        return out
