"""Calibrated, chaos-instrumented fleet simulator (the digital twin).

One process hosts hundreds of mocker workers — real scheduler, real page
pool, real KV events, fake accelerator — on the in-proc request plane
(`runtime/request_plane.py` `inproc://`), behind the real frontend stack
(ModelWatcher → Migration → router). `SimTiming` can be calibrated from
flight-recorder dumps (`SimTiming.fit_records`), traffic comes from the
scenario matrix (bench/loadgen.py: agentic/rag/json/burst), and a
`FaultSchedule` injects the failures production will eventually serve up:

  kill         SIGKILL a worker mid-stream (endpoint aborted, digests
               silenced, discovery unregistered — clients see the
               migratable `disconnected`, the indexer sees the delete)
  restart      bring a fresh worker up in a killed worker's slot
  partition    request-plane connect/send/recv raise ConnectionResetError
               for a window (per worker or fleet-wide)
  delay        request-plane edges sleep `param` seconds for a window
  corrupt_kv   garble on-disk KV tier blocks (disk_pool quarantine path)
  digest_drop  the worker's fleet digests are silently dropped
  digest_dup   every digest is published twice (observer seq dedup path)

Schedule grammar (`FaultSchedule.parse`): events joined by `;`, each

  kind@START[+DURATION][:wIDX|w*][=PARAM]

  kill@10:w3                 kill worker 3 at t=10s (trace clock)
  partition@20+5:w1          cut worker 1's request plane for 5s
  delay@30+10:w*=0.05        50ms added to every plane edge for 10s
  corrupt_kv@40:w2=4         garble 4 disk-tier blocks of worker 2
  digest_drop@50+20:w4       worker 4 goes digest-silent for 20s
  restart@60:w3              new worker in slot 3

`FleetSim.run()` reports router p50 decision time, migration success
rate, SLO attainment (goodput + SLO-engine state), and fault counts.
The whole run is seeded: same seed + same schedule → same token streams,
same winners, same report shape.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dynamo_tpu.bench.loadgen import (
    aggregate_migration,
    aggregate_phases,
    compute_goodput,
    compute_scenario_matrix,
    generate_scenarios,
    run_sessions_against_engine,
)
from dynamo_tpu.frontend.service import ModelManager, ModelWatcher
from dynamo_tpu.mocker.__main__ import build_mock_engine
from dynamo_tpu.mocker.__main__ import parse_args as mocker_args
from dynamo_tpu.runtime import request_plane as rp
from dynamo_tpu.runtime.discovery import MemDiscovery
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.event_plane import FLEET_DIGEST_SUBJECT

log = logging.getLogger("dynamo_tpu.fleet_sim")

_EVENT_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<at>[0-9.]+)"
    r"(?:\+(?P<dur>[0-9.]+))?"
    r"(?::w(?P<worker>\d+|\*))?"
    r"(?:=(?P<param>[0-9.]+))?$"
)

FAULT_KINDS = ("kill", "restart", "partition", "delay", "corrupt_kv",
               "digest_drop", "digest_dup", "partition_slice")


@dataclass
class FaultEvent:
    kind: str
    at_s: float  # trace-clock offset into the run
    duration_s: float = 0.0  # windowed faults; 0 = instantaneous
    worker: Optional[int] = None  # worker slot index; None = fleet-wide
    param: float = 0.0  # kind-specific (delay seconds, corrupt count)

    def to_text(self) -> str:
        s = f"{self.kind}@{self.at_s:g}"
        if self.duration_s:
            s += f"+{self.duration_s:g}"
        s += ":w*" if self.worker is None else f":w{self.worker}"
        if self.param:
            s += f"={self.param:g}"
        return s


class FaultSchedule:
    def __init__(self, events: List[FaultEvent]):
        self.events = sorted(events, key=lambda e: e.at_s)

    def __len__(self) -> int:
        return len(self.events)

    def to_text(self) -> str:
        return ";".join(e.to_text() for e in self.events)

    @classmethod
    def parse(cls, text: str) -> "FaultSchedule":
        events = []
        for raw in text.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            m = _EVENT_RE.match(raw)
            if m is None:
                raise ValueError(f"bad fault event {raw!r} "
                                 "(kind@start[+dur][:wIDX|w*][=param])")
            kind = m.group("kind")
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (have {FAULT_KINDS})")
            w = m.group("worker")
            events.append(FaultEvent(
                kind=kind,
                at_s=float(m.group("at")),
                duration_s=float(m.group("dur") or 0.0),
                worker=None if w in (None, "*") else int(w),
                param=float(m.group("param") or 0.0),
            ))
        return cls(events)

    @classmethod
    def generate(
        cls,
        seed: int,
        n_workers: int,
        duration_s: float,
        kills_per_min: float = 1.0,
        restart_after_s: float = 20.0,
        partitions_per_min: float = 0.5,
        partition_s: float = 5.0,
        digest_faults_per_min: float = 0.5,
        digest_fault_s: float = 15.0,
    ) -> "FaultSchedule":
        """The worker-death day: Poisson kill arrivals, each followed by a
        restart into the same slot, plus partition and digest-loss
        windows. Deterministic per seed."""
        rng = random.Random(seed)
        events: List[FaultEvent] = []

        def arrivals(rate_per_min: float):
            t = 0.0
            while rate_per_min > 0:
                t += rng.expovariate(rate_per_min / 60.0)
                if t >= duration_s:
                    return
                yield t

        for t in arrivals(kills_per_min):
            w = rng.randrange(n_workers)
            events.append(FaultEvent("kill", t, worker=w))
            if t + restart_after_s < duration_s:
                events.append(
                    FaultEvent("restart", t + restart_after_s, worker=w))
        for t in arrivals(partitions_per_min):
            events.append(FaultEvent(
                "partition", t, duration_s=partition_s,
                worker=rng.randrange(n_workers)))
        for t in arrivals(digest_faults_per_min):
            kind = rng.choice(("digest_drop", "digest_dup"))
            events.append(FaultEvent(
                kind, t, duration_s=digest_fault_s,
                worker=rng.randrange(n_workers)))
        return cls(events)


@dataclass
class SimWorker:
    idx: int
    runtime: DistributedRuntime
    served: Any  # ServedWorker
    engine: Any
    alive: bool = True
    disk_root: Optional[str] = None
    digest_state: Dict[str, float] = field(default_factory=dict)


class _FaultyDigestPublisher:
    """EventPublisher proxy in front of a worker's digest publishes:
    drops or duplicates FLEET_DIGEST_SUBJECT payloads per the fault
    windows in `state` ({"drop_until": t, "dup_until": t}, loop clock).
    Everything else passes through untouched."""

    def __init__(self, pub, state: Dict[str, float]):
        self._pub = pub
        self._state = state

    @property
    def address(self) -> str:
        return self._pub.address

    async def publish(self, subject: str, payload: Any) -> None:
        if subject == FLEET_DIGEST_SUBJECT:
            now = asyncio.get_event_loop().time()
            if now < self._state.get("drop_until", 0.0):
                return
            await self._pub.publish(subject, payload)
            if now < self._state.get("dup_until", 0.0):
                await self._pub.publish(subject, payload)
            return
        await self._pub.publish(subject, payload)


class FleetSim:
    """N mocker workers + real frontend stack in one process, with the
    fault-injection plane wired through the in-proc transport."""

    def __init__(
        self,
        n_workers: int,
        router_mode: str = "kv",
        seed: int = 0,
        speed: float = 0.02,  # SimTiming scale (0 = no sleeps)
        decode_base_ms: float = 4.0,
        idle_sleep_s: float = 0.05,  # engine-thread idle poll (see below)
        num_pages: int = 128,
        page_size: int = 16,
        max_batch: int = 16,
        timing=None,  # calibrated SimTiming override (fit_records)
        digest_period_s: float = 1.0,
        digest_window_s: float = 5.0,
        slo: str = "ttft:p99<2.0,itl:p50<0.05",
        migration_limit: int = 3,
        migration_backoff_base_s: float = 0.02,
        sick_cooldown_s: float = 2.0,
        session_affinity_ttl: Optional[float] = None,
        host_kv_blocks: int = 0,  # G2 tier; auto-enabled by disk_kv_blocks
        disk_kv_blocks: int = 0,
        disk_kv_base: Optional[str] = None,  # per-worker roots under here
        disk_kv_bytes: Optional[int] = None,  # G3 byte budget (spills →G4)
        obj_kv_base: Optional[str] = None,  # ONE shared G4 root for the
        #   whole fleet (content-addressed → fleet-wide prefix dedup);
        #   None with slices > 1 auto-provisions one under disk_kv_base
        slices: int = 1,  # ICI islands: worker i lives on slice i%slices;
        #   cross-slice peer pulls pay the DCN charge below
        dcn_delay_s: float = 0.0,  # per-pull latency on cross-slice KV
        #   fetches (the declarative multi-slice topology, realized via
        #   the same loop-clock charging as the per-edge delay plane)
        sanitize: bool = True,  # fleet-sim default harness: one shared
        #   non-strict Sanitizer across all workers; run() reports its
        #   block and chaos tests assert zero violations
        mixed_prefill_tokens: int = 256,  # per-worker co-scheduling knobs
        mixed_prefill_seqs: int = 8,      # (the actuator retunes these live)
        spec_ngram: bool = False,
        spec_k: int = 4,
        spec_accept_rate: Optional[float] = None,
        actuate: bool = False,  # run the planner actuation engine live:
        #   sense (FleetLoadObserver + SloEngine) → decide → rehearse in a
        #   twin fork → apply (retune/drain in-proc, scale via the
        #   VirtualConnector handshake + this sim's decision poller)
        actuator_config=None,  # planner.actuator.ActuatorConfig override
        decisions_root: Optional[str] = None,  # VirtualConnector root
        shadow: Any = "twin",  # "twin" = TwinRehearsal fork oracle,
        #   "off"/None = apply unrehearsed, or a custom oracle object
        install_fault_hook: bool = True,  # rehearsal forks run inside a
        #   live sim and must NOT touch the module-global in-proc fault
        #   hook (it belongs to the outer experiment)
        incident_dir: Optional[str] = None,  # arm black-box forensics:
        #   on fleet SLO BREACH, sanitizer violation, or flight-recorder
        #   anomaly, snapshot a correlated bundle here (runtime/incident.py)
        incident_min_interval_s: float = 5.0,
        incident_max_bundles: int = 8,
    ):
        self.n_workers = n_workers
        self.router_mode = router_mode
        self.seed = seed
        self.speed = speed
        self.decode_base_ms = decode_base_ms
        # hundreds of engine step threads in one process: a 2ms idle poll
        # x 500 threads is 250k wakeups/s of pure GIL churn — widen it
        self.idle_sleep_s = idle_sleep_s
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_batch = max_batch
        self.timing = timing
        self.digest_period_s = digest_period_s
        self.digest_window_s = digest_window_s
        self.slo = slo
        self.migration_limit = migration_limit
        self.migration_backoff_base_s = migration_backoff_base_s
        self.sick_cooldown_s = sick_cooldown_s
        self.session_affinity_ttl = session_affinity_ttl
        # the disk tier spills from the host tier: G3 implies G2
        if disk_kv_blocks > 0 and host_kv_blocks <= 0:
            host_kv_blocks = max(8, disk_kv_blocks // 2)
        self.host_kv_blocks = host_kv_blocks
        self.disk_kv_blocks = disk_kv_blocks
        self.disk_kv_base = disk_kv_base
        self.disk_kv_bytes = disk_kv_bytes
        self.slices = max(1, int(slices))
        self.dcn_delay_s = float(dcn_delay_s)
        self.obj_kv_base = obj_kv_base  # explicit root, or None =
        #   auto-provision under the realm (see _obj_root)
        # slice-level partitions: label -> loop-clock deadline; an active
        # entry severs every cross-slice pull touching that slice
        self._slice_partitions: Dict[str, float] = {}
        self.mixed_prefill_tokens = mixed_prefill_tokens
        self.mixed_prefill_seqs = mixed_prefill_seqs
        self.spec_ngram = spec_ngram
        self.spec_k = spec_k
        self.spec_accept_rate = spec_accept_rate
        self.actuate = actuate
        self.actuator_config = actuator_config
        self.decisions_root = decisions_root
        self.shadow = shadow
        self._install_fault_hook = install_fault_hook
        self.incident_dir = incident_dir
        self.incident_min_interval_s = incident_min_interval_s
        self.incident_max_bundles = incident_max_bundles
        self.incidents = None  # runtime/incident.py IncidentCapturer
        self._incident_task: Optional[asyncio.Task] = None
        self._incident_viol_seen = 0  # sanitizer violations already seen
        self.actuator = None
        self.connector = None
        self._decision_poller: Optional[asyncio.Task] = None
        self._decision_offset = 0
        self.scale_events: Dict[str, int] = {}  # up/down applied by poller

        self.realm = f"fleet-{seed}-{os.getpid()}-{id(self):x}"
        self.workers: List[SimWorker] = []
        self.frontend_runtime: Optional[DistributedRuntime] = None
        self.manager: Optional[ModelManager] = None
        self.watcher: Optional[ModelWatcher] = None
        self.observer = None
        self.slo_engine = None
        self._digest_watch: Optional[asyncio.Task] = None
        self._addr_to_idx: Dict[str, int] = {}
        self._iid_to_idx: Dict[int, int] = {}  # instance id -> worker slot
        # fault state consulted by the in-proc fault hook; keys are worker
        # slot indices or "*" (fleet-wide), values are loop-clock deadlines
        self._partitions: Dict[Any, float] = {}
        self._delays: Dict[Any, tuple] = {}  # key -> (until, seconds)
        self.fault_counts: Dict[str, int] = {}
        self.sanitizer = None
        if sanitize:
            from dynamo_tpu.runtime.sanitizer import Sanitizer

            # non-strict: chaos faults must play out and the report show
            # every violation, not die on the first
            self.sanitizer = Sanitizer(strict=False)

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        if self._install_fault_hook:
            # the outer (real) experiment also owns process-wide tracing:
            # env-gated + idempotent, so a bare sim is a no-op and a
            # DYN_TRACE_RING run arms ONE shared ring that every in-proc
            # worker exports into — the fleet merge comes for free
            from dynamo_tpu.runtime.tracing import configure_tracing

            configure_tracing(service_name="fleet-sim")
            rp.set_inproc_fault_hook(self._fault_hook)
        if self.sanitizer is not None:
            self.sanitizer.start_watchdog()
        if self.incident_dir:
            # armed BEFORE workers spawn so each engine's flight-recorder
            # anomaly hook can pull the trigger from its step thread
            self._arm_incidents()
        for i in range(self.n_workers):
            await self._spawn_worker(i)
        frt = DistributedRuntime(
            discovery=MemDiscovery(realm=self.realm),
            event_transport="inproc", request_plane="inproc",
        )
        self.frontend_runtime = frt
        self.manager = ModelManager()
        self.watcher = ModelWatcher(
            frt, self.manager, router_mode=self.router_mode,
            migration_limit=self.migration_limit,
            session_affinity_ttl=self.session_affinity_ttl,
        )
        await self.watcher.start()
        await self.watcher.wait_for_model(timeout=30)
        from dynamo_tpu.frontend.migration import Migration
        from dynamo_tpu.planner.slo import SloEngine, parse_slo_config
        from dynamo_tpu.runtime.fleet_observer import FleetObserver

        # the chaos schedule compresses days into seconds — scale the
        # retry backoff and failure-cache TTL with it
        for entry in self.manager.models.values():
            stage = (entry.chain.get("migration")
                     if hasattr(entry.chain, "get") else None)
            if isinstance(stage, Migration):
                stage.backoff_base_s = self.migration_backoff_base_s
            client = getattr(entry, "client", None)
            router = getattr(client, "router", None)
            if router is not None:
                router.sick_cooldown_s = self.sick_cooldown_s
        self.observer = FleetObserver(
            frt.event_subscriber([FLEET_DIGEST_SUBJECT]),
            window_s=self.digest_window_s,
        )
        await self.observer.start()
        self.slo_engine = SloEngine(self.observer, parse_slo_config(self.slo))
        # topology-aware placement: the routers' tier_cost_fn closes over
        # this attribute, so binding after sink construction still works
        self.watcher.tier_cost_source = self.observer.onboard_costs

        async def _watch_digests():
            try:
                async for ev in frt.discovery.watch("services/"):
                    addr = (ev.instance.metadata or {}).get("digest_publisher")
                    if ev.kind == "put" and addr:
                        self.observer.connect_publisher(addr)
                    elif ev.kind == "delete":
                        # a dead worker's digests must leave the load
                        # aggregates NOW, not at the 3x-window age-out —
                        # the actuator otherwise scales against ghost load
                        self.observer.forget_instance(
                            ev.instance.instance_id)
            except asyncio.CancelledError:
                pass

        self._digest_watch = asyncio.get_running_loop().create_task(
            _watch_digests())
        if self.incidents is not None:
            self._incident_task = asyncio.get_running_loop().create_task(
                self._incident_watch())
        if self.actuate:
            await self._start_actuator()

    async def _start_actuator(self) -> None:
        from dynamo_tpu.planner.actuator import Actuator, ActuatorConfig
        from dynamo_tpu.planner.connector import VirtualConnector
        from dynamo_tpu.planner.observer import FleetLoadObserver

        root = self.decisions_root or os.path.join(
            "/tmp/fleet_actuator", self.realm)
        self.connector = VirtualConnector(root)
        loads = FleetLoadObserver(self.observer,
                                  window_s=self.digest_window_s)
        oracle = self.shadow
        if oracle == "twin":
            from dynamo_tpu.planner.shadow import TwinRehearsal

            oracle = TwinRehearsal(self._recorder_records, self.live_state)
        elif oracle in ("off", False):
            oracle = None
        cfg = self.actuator_config
        if cfg is None:
            # scale the anti-flap clocks with the sim's digest cadence:
            # a compressed day ticks in sub-second periods
            cfg = ActuatorConfig(
                tick_interval_s=max(0.25, self.digest_period_s),
                hysteresis_ticks=2,
                cooldown_s=2.0 * self.digest_window_s,
                flap_guard_s=4.0 * self.digest_window_s,
                min_samples=2,
                component="decode",
            )
        self.actuator = Actuator(
            loads, self.slo_engine, self.connector, cfg,
            shadow=oracle,
            affinity=getattr(self.watcher, "affinity", None),
            retune_fn=self._retune_by_worker,
            drain_fn=self._drain_by_worker,
            replicas_fn=self.alive_workers,
        )
        self.actuator.start()
        self._decision_poller = asyncio.get_running_loop().create_task(
            self._poll_decisions())

    async def _spawn_worker(self, idx: int) -> SimWorker:
        from dynamo_tpu.worker_common import serve_worker

        rt = DistributedRuntime(
            discovery=MemDiscovery(realm=self.realm),
            event_transport="inproc", request_plane="inproc",
        )
        flags = [
            "--speed", str(self.speed),
            "--decode-base-ms", str(self.decode_base_ms),
            "--page-size", str(self.page_size),
            "--num-pages", str(self.num_pages),
            "--max-batch", str(self.max_batch),
            "--mixed-prefill-tokens", str(self.mixed_prefill_tokens),
            "--mixed-prefill-seqs", str(self.mixed_prefill_seqs),
        ]
        if self.spec_ngram:
            flags += ["--spec-ngram", "--spec-k", str(self.spec_k)]
            if self.spec_accept_rate is not None:
                flags += ["--spec-accept-rate", str(self.spec_accept_rate)]
        if self.host_kv_blocks > 0:
            flags += ["--host-kv-blocks", str(self.host_kv_blocks)]
        disk_root = None
        if self.disk_kv_blocks > 0:
            base = self.disk_kv_base or "/tmp/fleet_sim_kv"
            disk_root = os.path.join(base, self.realm, f"w{idx}")
            os.makedirs(disk_root, exist_ok=True)
            # real (tiny) KV bytes so the disk tier writes actual files —
            # corrupt_kv garbles them and the quarantine path runs for real
            flags += ["--disk-kv-blocks", str(self.disk_kv_blocks),
                      "--disk-kv-root", disk_root, "--kv-export-bytes"]
            if self.disk_kv_bytes:
                flags += ["--disk-kv-bytes", str(self.disk_kv_bytes)]
        obj_root = self._obj_root()
        if obj_root:
            os.makedirs(obj_root, exist_ok=True)
            flags += ["--obj-kv-root", obj_root]
        if self.slices > 1:
            flags += ["--slice-id", self.slice_of(idx)]
        margs = mocker_args(flags)
        engine, card = build_mock_engine(
            margs, timing=self.timing, idle_sleep_s=self.idle_sleep_s,
            sanitizer=self.sanitizer)
        rec = getattr(engine, "recorder", None)
        if (self.incidents is not None and rec is not None
                and getattr(rec, "enabled", False)):
            # fires on the engine step thread; trigger() is the sanctioned
            # non-blocking hand-off (DYN-R004) — never snapshot inline here
            cap = self.incidents

            def _on_anomaly(r, _w=idx, _cap=cap):
                _cap.trigger("recorder_anomaly", {
                    "worker": _w, "iteration": int(r.seq),
                    "wall_s": float(r.wall_s), "kind": r.kind,
                })

            rec.on_anomaly(_on_anomaly)
        digest_state: Dict[str, float] = {}
        served = await serve_worker(
            rt, engine, card, digest_period_s=self.digest_period_s)
        if self.slices > 1 and getattr(engine, "remote_kv_fetch", None):
            # multi-slice topology: cross-slice peer pulls pay the DCN
            # charge (or sever under a slice partition). Wrapping the
            # fetch — which _pull_remote_host times — means the worker's
            # measured remote EWMA honestly reflects the link class.
            inner = engine.remote_kv_fetch

            async def _fetch(hint, _inner=inner, _src=idx):
                await self._charge_link(_src, hint)
                return await _inner(hint)

            engine.remote_kv_fetch = _fetch
        if served.digest_pub is not None:
            served.digest_pub.pub = _FaultyDigestPublisher(
                served.digest_pub.pub, digest_state)
        w = SimWorker(idx=idx, runtime=rt, served=served, engine=engine,
                      disk_root=disk_root, digest_state=digest_state)
        if idx < len(self.workers):
            self.workers[idx] = w
        else:
            self.workers.append(w)
        self._addr_to_idx[rt.server.address] = idx
        self._iid_to_idx[served.instance.instance_id] = idx
        return w

    async def stop(self) -> None:
        if self.actuator is not None:
            await self.actuator.stop()
        if self._decision_poller is not None:
            self._decision_poller.cancel()
            self._decision_poller = None
        if self._incident_task is not None:
            self._incident_task.cancel()
            self._incident_task = None
        if self._digest_watch is not None:
            self._digest_watch.cancel()
        if self.observer is not None:
            await self.observer.stop()
        if self.watcher is not None:
            await self.watcher.stop()
        if self.frontend_runtime is not None:
            await self.frontend_runtime.shutdown(drain_timeout=1)
        for w in self.workers:
            if w.alive:
                try:
                    await w.served.stop()
                    await w.runtime.shutdown(drain_timeout=1)
                except Exception:
                    log.debug("worker %d teardown failed", w.idx,
                              exc_info=True)
        if self.sanitizer is not None:
            await self.sanitizer.stop_watchdog()
            self.sanitizer.audit_tasks()
        if self.incidents is not None:
            # drain off the loop: close() joins the writer thread, which
            # may be mid-bundle (snapshot + JSONL write)
            await asyncio.to_thread(self.incidents.close, 5.0)
        if self._install_fault_hook:
            rp.set_inproc_fault_hook(None)

    # -- black-box forensics -----------------------------------------------
    def _arm_incidents(self) -> None:
        """Wire the incident capturer's evidence sources. Every source is
        a snapshot-style read (lambdas re-resolve live objects at capture
        time — the actuator, for instance, starts after arming). The
        bundle deliberately carries `live_state` + `recorder` so
        `scripts/dyn_incident.py replay` can fit a SimTiming and fork a
        twin of the fleet as it was tuned at the moment of the breach."""
        from dynamo_tpu.runtime.incident import IncidentCapturer

        cap = IncidentCapturer(
            self.incident_dir,
            min_interval_s=self.incident_min_interval_s,
            max_bundles=self.incident_max_bundles,
        )
        cap.register("live_state", self.live_state)
        cap.register("slo", lambda: (
            self.slo_engine.evaluate() if self.slo_engine else {}))
        cap.register("digests", lambda: (
            self.observer.window_digests(None) if self.observer else {}))
        cap.register("kv_links", lambda: (
            self.observer.onboard_costs(None) if self.observer else {}))
        cap.register("routing", self._routing_section)
        cap.register("recorder", self._recorder_records)
        cap.register("traces", self._trace_section)
        cap.register("faults", lambda: dict(self.fault_counts))
        cap.register("actuator", lambda: (
            [d.to_dict() for d in self.actuator.journal.decisions(64)]
            if self.actuator else []))
        if self.sanitizer is not None:
            cap.register("sanitizer", self.sanitizer.report)
        self.incidents = cap

    def _routing_section(self):
        from dynamo_tpu.runtime.fleet_observer import routing_debug_payload

        if self.manager is None:
            return {}
        return routing_debug_payload(
            self.manager.routing_audits(), last_n=256)

    @staticmethod
    def _trace_section():
        """The breaching window's spans: the process span ring read
        UNSAMPLED (evidence beats budgets), plus the tail-marked trace
        ids the sampler would have kept anyway."""
        from dynamo_tpu.runtime import tracing

        ring = tracing.span_ring()
        if ring is None:
            return {"n": 0, "spans": [],
                    "note": "span ring not armed (set DYN_TRACE_RING)"}
        spans = ring.snapshot(last_n=2048, sampled=False)
        return {
            "n": len(spans),
            "tail_traces": ring.tail_trace_ids(),
            "spans": [tracing.span_to_dict(s) for s in spans],
        }

    async def _incident_watch(self) -> None:
        """Poll the SLO engine and sanitizer on the digest cadence; pull
        the trigger on the OK/WARN -> BREACH transition (not while it
        stays breached — the rate limiter backs that up) and on every
        fresh sanitizer violation batch."""
        prev_state = "OK"
        try:
            while True:
                await asyncio.sleep(max(0.25, self.digest_period_s))
                cap = self.incidents
                if cap is None:
                    return
                state = prev_state
                if self.slo_engine is not None:
                    try:
                        view = self.slo_engine.evaluate()
                    except Exception:
                        log.debug("incident SLO poll failed", exc_info=True)
                        view = {}
                    state = view.get("state") or prev_state
                    if state == "BREACH" and prev_state != "BREACH":
                        breached = sorted(
                            name for name, s in
                            (view.get("fleet") or {}).items()
                            if s.get("state") == "BREACH")
                        cap.trigger("slo_breach", {
                            "targets": breached,
                            "workers_alive": self.alive_workers(),
                        })
                prev_state = state
                if self.sanitizer is not None:
                    n = len(self.sanitizer.violations)
                    if n > self._incident_viol_seen:
                        last = self.sanitizer.violations[-1]
                        self._incident_viol_seen = n
                        cap.trigger("sanitizer_violation", {
                            "violations": n,
                            "kind": last.get("kind"),
                            "message": last.get("message"),
                        })
        except asyncio.CancelledError:
            pass

    # -- multi-slice topology ----------------------------------------------
    def slice_of(self, idx: int) -> str:
        """Worker slot -> slice label. Round-robin so labels stay stable
        for slots appended by scale-up."""
        return f"s{idx % self.slices}"

    def _obj_root(self) -> Optional[str]:
        """The fleet-shared G4 directory: ONE root for every worker —
        that sharing is what makes content-hash dedup fleet-wide."""
        if self.obj_kv_base:
            return self.obj_kv_base
        if self.slices > 1 and self.disk_kv_blocks > 0:
            return os.path.join(self.disk_kv_base or "/tmp/fleet_sim_kv",
                                self.realm, "g4_shared")
        return None

    async def _charge_link(self, src_idx: int, hint: Dict[str, Any]) -> None:
        """Charge the link class of a peer KV pull: same-slice = free
        (ICI is modeled as transport baseline), cross-slice = the DCN
        delay, severed entirely while either slice is partitioned. Runs
        inside the timed fetch, so measured remote EWMAs see it."""
        dst_idx = self._iid_to_idx.get(int(hint.get("instance") or 0))
        if dst_idx is None:
            return
        a, b = self.slice_of(src_idx), self.slice_of(dst_idx)
        if a == b:
            return
        now = asyncio.get_event_loop().time()
        for s in (a, b):
            p = self._slice_partitions.get(s)
            if p is not None and now < p:
                raise ConnectionResetError(
                    f"slice {s} partitioned ({a}<->{b} pull)")
        d = self._delays.get(("edge", a, b)) or self._delays.get(
            ("edge", b, a))
        if d is not None and now < d[0]:
            await asyncio.sleep(d[1])
        elif self.dcn_delay_s > 0:
            await asyncio.sleep(self.dcn_delay_s)

    def partition_slice(self, slice_label: str, duration_s: float) -> None:
        """Sever every cross-slice KV pull into/out of a slice. Pulls
        degrade to local rehydration/recompute via _pull_remote_host's
        failure path — requests keep streaming."""
        self._count("partition_slice")
        self._slice_partitions[str(slice_label)] = (
            asyncio.get_event_loop().time() + duration_s)

    def delay_edge(self, a: str, b: str, duration_s: float,
                   delay_s: float) -> None:
        """Per-edge DCN degradation between two slices (overrides the
        uniform dcn_delay_s while active)."""
        self._count("delay_edge")
        self._delays[("edge", str(a), str(b))] = (
            asyncio.get_event_loop().time() + duration_s, delay_s)

    def kv_fabric_report(self) -> Dict[str, Any]:
        """Fleet-wide fabric counters: G4 occupancy/dedup, promoted-from-
        G4 bytes, and the router's prefix-economy actions."""
        out = {"slices": self.slices, "dedup_hits": 0,
               "dedup_bytes_saved": 0, "obj_stored_bytes": 0,
               "obj_blocks": 0, "bytes_promoted_g4": 0,
               "replications": 0, "hot_trunks": 0}
        for w in self.workers:
            hp = getattr(w.engine, "host_pool", None)
            obj = getattr(hp, "obj", None)
            if obj is not None:
                st = getattr(obj, "stats", {})
                out["dedup_hits"] += int(st.get("dedup_hits", 0))
                out["dedup_bytes_saved"] += int(
                    st.get("dedup_bytes_saved", 0))
                out["obj_stored_bytes"] += int(st.get("stored_bytes", 0))
                out["obj_blocks"] = max(out["obj_blocks"], len(obj))
            pf = getattr(w.engine, "prefetch", None)
            if pf is not None:
                out["bytes_promoted_g4"] += int(
                    getattr(pf, "stats", {}).get("bytes_promoted_g4", 0))
        for entry in (self.manager.models if self.manager else {}).values():
            kvr = getattr(getattr(entry, "sink", None), "router", None)
            ps = getattr(kvr, "prefix_stats", None)
            if isinstance(ps, dict):
                out["replications"] += int(ps.get("replications", 0))
                out["hot_trunks"] += int(ps.get("hot_trunks", 0))
        stored = out["obj_stored_bytes"]
        out["dedup_ratio"] = round(
            (stored + out["dedup_bytes_saved"]) / stored, 3) if stored else 0.0
        return out

    # -- fault plane -------------------------------------------------------
    async def _fault_hook(self, direction: str, address: str) -> None:
        idx = self._addr_to_idx.get(address)
        now = asyncio.get_event_loop().time()
        for key in (idx, "*"):
            if key is None:
                continue
            d = self._delays.get(key)
            if d is not None and now < d[0]:
                await asyncio.sleep(d[1])
            p = self._partitions.get(key)
            if p is not None and now < p:
                raise ConnectionResetError(f"partitioned: {address}")

    def _count(self, kind: str) -> None:
        self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1

    async def kill_worker(self, idx: int) -> None:
        """SIGKILL twin: the endpoint vanishes mid-frame (clients see
        `disconnected`), digests go silent WITHOUT a flush, discovery gets
        the delete (indexer expiry + router instance removal), and the
        engine thread is joined. No goodbyes anywhere."""
        w = self.workers[idx]
        if not w.alive:
            return
        w.alive = False
        self._count("kill")
        w.runtime.server.abort()
        dp = w.served.digest_pub
        if dp is not None:
            if dp._task is not None:
                dp._task.cancel()
                dp._task = None

            async def _silent() -> None:
                return None

            dp.publish_once = _silent  # teardown must not flush a corpse
        if w.runtime._hb_task is not None:
            w.runtime._hb_task.cancel()
        for inst in list(w.runtime._served):
            try:
                await w.runtime.discovery.unregister(inst)
            except Exception:
                log.debug("unregister during kill failed", exc_info=True)
        w.runtime._served.clear()
        w.engine.stop()

    async def restart_worker(self, idx: int) -> None:
        w = self.workers[idx]
        if w.alive:
            return
        self._count("restart")
        self._addr_to_idx.pop(w.runtime.server.address, None)
        await self._spawn_worker(idx)

    def partition(self, idx: Optional[int], duration_s: float) -> None:
        self._count("partition")
        key = "*" if idx is None else idx
        self._partitions[key] = (
            asyncio.get_event_loop().time() + duration_s)

    def delay(self, idx: Optional[int], duration_s: float,
              delay_s: float) -> None:
        self._count("delay")
        key = "*" if idx is None else idx
        self._delays[key] = (
            asyncio.get_event_loop().time() + duration_s, delay_s)

    def corrupt_kv(self, idx: int, n_blocks: int = 4) -> int:
        """Garble on-disk KV tier blocks of worker `idx`. disk_pool's
        quarantine must treat each as a miss (unlink + recompute), never
        raise into the onboard path."""
        w = self.workers[idx]
        self._count("corrupt_kv")
        if not w.disk_root or not os.path.isdir(w.disk_root):
            return 0
        files = []
        for dirpath, _, names in os.walk(w.disk_root):
            files.extend(os.path.join(dirpath, f) for f in names)
        files.sort()
        rng = random.Random(self.seed ^ (idx << 8) ^ len(files))
        rng.shuffle(files)
        corrupted = 0
        for path in files[:n_blocks]:
            try:
                with open(path, "r+b") as f:
                    f.truncate(max(1, os.path.getsize(path) // 3))
                corrupted += 1
            except OSError:
                continue
        return corrupted

    def digest_fault(self, idx: int, kind: str, duration_s: float) -> None:
        self._count(kind)
        key = "drop_until" if kind == "digest_drop" else "dup_until"
        w = self.workers[idx]
        w.digest_state[key] = asyncio.get_event_loop().time() + duration_s

    # -- actuation plane ---------------------------------------------------
    def _routers(self) -> List[Any]:
        out = []
        for entry in (self.manager.models if self.manager else {}).values():
            router = getattr(getattr(entry, "client", None), "router", None)
            if router is not None:
                out.append(router)
        return out

    def _recorder_records(self) -> List[Any]:
        """The recent flight-recorder window across live workers — the
        calibration feed for shadow rehearsal (SimTiming.fit_records)."""
        records: List[Any] = []
        for w in self.workers:
            if not w.alive:
                continue
            rec = getattr(w.engine, "recorder", None)
            if rec is not None and getattr(rec, "enabled", False):
                records.extend(rec.snapshot(256))
        return records

    def live_state(self) -> Dict[str, Any]:
        """Fork-from-live-state snapshot: everything
        `FleetSim.fork_from_live` needs to rebuild a miniature of THIS
        fleet as currently tuned (live retunes included — knobs are read
        off a live engine, not the constructor args)."""
        alive = [w for w in self.workers if w.alive]
        sched = alive[0].engine.scheduler if alive else None
        return {
            "n_workers": len(alive) or self.n_workers,
            "router_mode": self.router_mode,
            "seed": self.seed,
            "speed": self.speed,
            "decode_base_ms": self.decode_base_ms,
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "max_batch": self.max_batch,
            "mixed_prefill_tokens": int(getattr(
                sched, "mixed_prefill_tokens", self.mixed_prefill_tokens)),
            "mixed_prefill_seqs": int(getattr(
                sched, "mixed_prefill_seqs", self.mixed_prefill_seqs)),
            "spec_ngram": self.spec_ngram,
            "spec_k": int(getattr(alive[0].engine, "spec_k", self.spec_k)
                          if alive else self.spec_k),
            "spec_accept_rate": self.spec_accept_rate,
            "slo": self.slo,
            "session_affinity_ttl": self.session_affinity_ttl,
        }

    @classmethod
    def fork_from_live(cls, state: Dict[str, Any], *, timing=None,
                       overrides: Optional[Dict[str, Any]] = None
                       ) -> "FleetSim":
        """Build a rehearsal fork from a `live_state()` snapshot.
        `overrides` mutates the candidate world (n_workers /
        mixed_prefill_tokens / mixed_prefill_seqs / spec_k); everything
        else — knob values, router mode, page geometry — carries over.
        The fork never installs the global in-proc fault hook, runs
        sanitizer-off, and gets its own discovery realm and seed, so it
        can run INSIDE a live sim without touching the experiment."""
        o = dict(overrides or {})
        n = int(o.pop("n_workers", state.get("n_workers") or 1))
        sim = cls(
            n_workers=max(1, n),
            router_mode=state.get("router_mode", "kv"),
            seed=int(state.get("seed", 0)) ^ 0xF0CC,
            speed=float(state.get("speed", 0.02)),
            decode_base_ms=float(state.get("decode_base_ms", 4.0)),
            idle_sleep_s=0.01,
            num_pages=int(state.get("num_pages", 128)),
            page_size=int(state.get("page_size", 16)),
            max_batch=int(state.get("max_batch", 16)),
            timing=timing,
            digest_period_s=0.5,
            digest_window_s=5.0,
            slo=state.get("slo") or "ttft:p99<2.0,itl:p50<0.05",
            session_affinity_ttl=state.get("session_affinity_ttl"),
            mixed_prefill_tokens=int(o.pop(
                "mixed_prefill_tokens",
                state.get("mixed_prefill_tokens", 256))),
            mixed_prefill_seqs=int(o.pop(
                "mixed_prefill_seqs", state.get("mixed_prefill_seqs", 8))),
            spec_ngram=bool(state.get("spec_ngram", False)),
            spec_k=int(o.pop("spec_k", state.get("spec_k", 4))),
            spec_accept_rate=state.get("spec_accept_rate"),
            sanitize=False,
            actuate=False,
            shadow="off",
            install_fault_hook=False,
        )
        if o:
            raise ValueError(f"unknown fork overrides: {sorted(o)}")
        return sim

    async def _retune_by_worker(self, worker, params: Dict[str, Any]
                                ) -> bool:
        """Actuator retune delivery: the in-proc analog of the worker
        `rl` admin endpoint. Returns False for unknown/dead workers."""
        idx = self._iid_to_idx.get(int(worker[0]))
        if idx is None or not self.workers[idx].alive:
            return False
        allowed = {k: v for k, v in params.items()
                   if k in ("mixed_prefill_tokens", "mixed_prefill_seqs",
                            "spec_k")}
        if not allowed:
            return False
        applied = self.workers[idx].engine.retune(**allowed)
        log.info("retuned worker %d: %s", idx, applied)
        return True

    async def _drain_by_worker(self, worker) -> bool:
        """Actuator drain delivery: mark the instance sick on every
        router so NEW traffic migrates off it. Session-affinity pins
        resolve before the sick filter, so bound session trees keep
        streaming to it until their TTL — no mid-session rebind."""
        iid = int(worker[0])
        idx = self._iid_to_idx.get(iid)
        if idx is None or not self.workers[idx].alive:
            return False
        routers = self._routers()
        for router in routers:
            router.mark_sick(iid, cooldown=10.0 * self.sick_cooldown_s)
        return bool(routers)

    async def _decommission_worker(self, idx: int,
                                   drain_timeout_s: float = 2.0) -> None:
        """Planner scale-down: the graceful opposite of kill_worker. New
        traffic routes away first (mark_sick), in-flight streams get
        `drain_timeout_s` to finish, then the worker tears down cleanly —
        digests flush, discovery sees the delete (which also drops its
        load rows via forget_instance)."""
        w = self.workers[idx]
        if not w.alive:
            return
        iid = w.served.instance.instance_id
        for router in self._routers():
            router.mark_sick(iid, cooldown=10.0 * drain_timeout_s)
        loop = asyncio.get_event_loop()
        deadline = loop.time() + drain_timeout_s
        while loop.time() < deadline and len(w.runtime.server._active):
            await asyncio.sleep(0.05)
        w.alive = False
        self._count("scale_down")
        self._addr_to_idx.pop(w.runtime.server.address, None)
        self._iid_to_idx.pop(iid, None)
        try:
            await w.served.stop()
            await w.runtime.shutdown(drain_timeout=1)
        except Exception:
            log.debug("decommission of worker %d failed", idx,
                      exc_info=True)

    async def _apply_scale(self, target: int) -> None:
        """Realize a connector scale decision against the twin fleet:
        revive dead slots (or append fresh ones) on the way up; on the
        way down, decommission workers carrying the FEWEST bound session
        trees first (AffinityCoordinator.snapshot) — draining respects
        sessions by construction."""
        target = max(1, int(target))
        alive = [w for w in self.workers if w.alive]
        if target > len(alive):
            need = target - len(alive)
            self.scale_events["up"] = self.scale_events.get("up", 0) + need
            for w in [w for w in self.workers if not w.alive][:need]:
                self._addr_to_idx.pop(w.runtime.server.address, None)
                await self._spawn_worker(w.idx)
                need -= 1
            for _ in range(need):
                await self._spawn_worker(len(self.workers))
        elif target < len(alive):
            excess = len(alive) - target
            self.scale_events["down"] = (
                self.scale_events.get("down", 0) + excess)
            bound: Dict[str, int] = {}
            aff = getattr(self.watcher, "affinity", None)
            if aff is not None:
                bound = aff.snapshot().get("by_instance") or {}
            victims = sorted(
                alive,
                key=lambda w: (
                    bound.get(f"{w.served.instance.instance_id:x}", 0),
                    -w.idx,
                ),
            )[:excess]
            for w in victims:
                await self._decommission_worker(w.idx)

    @staticmethod
    def _append_line(path, line: str) -> None:
        with open(path, "a") as f:
            f.write(line + "\n")

    async def _poll_decisions(self) -> None:
        """The external-actuator half of the VirtualConnector handshake,
        played by the twin: tail decisions.jsonl, realize each scale
        decision against the fleet, append the ack. This is the same
        file contract a k8s operator or LocalProcessConnector deployment
        would honor — the planner can't tell the difference."""
        path = self.connector.root / "decisions.jsonl"
        ack_path = self.connector.root / "acks.jsonl"
        try:
            while True:
                await asyncio.sleep(max(0.1, self.digest_period_s / 2))
                try:
                    text = await asyncio.to_thread(path.read_text)
                except FileNotFoundError:
                    continue
                lines = text.splitlines()
                fresh = lines[self._decision_offset:]
                self._decision_offset = len(lines)
                for line in fresh:
                    try:
                        d = json.loads(line)
                    except ValueError:
                        continue
                    await self._apply_scale(int(d.get("target_replicas", 0)))
                    ack = json.dumps({
                        "decision_id": d.get("decision_id"),
                        "ts": time.time(),
                        "applied_replicas": self.alive_workers(),
                    })
                    await asyncio.to_thread(
                        self._append_line, ack_path, ack)
        except asyncio.CancelledError:
            pass

    async def apply_event(self, ev: FaultEvent, time_scale: float = 1.0,
                          rng: Optional[random.Random] = None) -> None:
        idx = ev.worker
        if idx is None and ev.kind in ("kill", "restart", "corrupt_kv",
                                       "digest_drop", "digest_dup"):
            idx = (rng or random.Random(self.seed)).randrange(
                len(self.workers))
        dur = ev.duration_s * time_scale
        if ev.kind == "kill":
            await self.kill_worker(idx)
        elif ev.kind == "restart":
            await self.restart_worker(idx)
        elif ev.kind == "partition":
            self.partition(ev.worker, dur)
        elif ev.kind == "delay":
            self.delay(ev.worker, dur, ev.param)
        elif ev.kind == "corrupt_kv":
            # disk truncation walks + rewrites tier files: off the loop,
            # which carries every in-flight stream of the sim (DYN-A002)
            await asyncio.to_thread(self.corrupt_kv, idx, int(ev.param) or 4)
        elif ev.kind == "partition_slice":
            # param carries the numeric slice index (labels are s<i>)
            self.partition_slice(f"s{int(ev.param)}", dur)
        elif ev.kind in ("digest_drop", "digest_dup"):
            self.digest_fault(idx, ev.kind, dur)

    async def _fault_pump(self, schedule: FaultSchedule, t0: float,
                          time_scale: float) -> None:
        rng = random.Random(self.seed ^ 0x5EED)
        loop = asyncio.get_event_loop()
        try:
            for ev in schedule.events:
                delay = ev.at_s * time_scale - (loop.time() - t0)
                if delay > 0:
                    await asyncio.sleep(delay)
                await self.apply_event(ev, time_scale, rng)
        except asyncio.CancelledError:
            pass

    # -- views -------------------------------------------------------------
    def alive_workers(self) -> int:
        return sum(1 for w in self.workers if w.alive)

    def active_streams(self) -> int:
        """In-flight server-side requests across live workers — must be 0
        after a drained run (the zero-hung-streams assertion)."""
        return sum(len(w.runtime.server._active)
                   for w in self.workers if w.alive)

    @property
    def entry(self):
        return self.manager.get("mock-model")

    # -- the experiment ----------------------------------------------------
    async def run(
        self,
        scenarios=("agentic", "rag", "json", "burst"),
        n_sessions: int = 8,
        rps: float = 4.0,
        time_scale: float = 1.0,
        fault_schedule: Optional[FaultSchedule] = None,
        ttft_slo_s: float = 2.0,
        itl_slo_s: float = 0.05,
    ) -> Dict[str, Any]:
        """Drive the scenario matrix through the frontend chain while the
        fault pump walks the schedule; returns the twin's report."""
        scripts = generate_scenarios(
            list(scenarios), n_sessions, rps=rps, seed=self.seed)
        loop = asyncio.get_event_loop()
        t0 = loop.time()
        pump = None
        if fault_schedule is not None and len(fault_schedule):
            pump = loop.create_task(
                self._fault_pump(fault_schedule, t0, time_scale))
        try:
            results, duration = await run_sessions_against_engine(
                scripts, self.entry.chain.generate,
                time_scale=time_scale, seed=self.seed,
            )
        finally:
            if pump is not None:
                pump.cancel()
        report = compute_goodput(results, duration, ttft_slo_s, itl_slo_s)
        phases = aggregate_phases(results)
        route = phases.get("route_s") or {}
        mig = aggregate_migration(results)
        slo_view = self.slo_engine.evaluate() if self.slo_engine else {}
        out = {
            "workers": self.n_workers,
            "workers_alive": self.alive_workers(),
            "requests": len(results),
            "duration_s": round(duration, 3),
            "simulated_duration_s": round(
                duration / max(time_scale, 1e-9), 1),
            "rps": round(len(results) / max(duration, 1e-9), 2),
            "router_p50_decision_us": round(
                route.get("p50_s", 0.0) * 1e6, 1),
            "router_p95_decision_us": round(
                route.get("p95_s", 0.0) * 1e6, 1),
            "migration": mig,
            "migration_success_rate": mig.get("success_rate"),
            "slo_attainment": (report.n_slo_met / report.n_ok
                               if report.n_ok else 0.0),
            "slo_state": slo_view.get("state"),
            "goodput": json.loads(report.to_json()),
            "scenarios": compute_scenario_matrix(
                results, duration, ttft_slo_s, itl_slo_s),
            "faults": dict(self.fault_counts),
            "active_streams_after": self.active_streams(),
        }
        if self.slices > 1 or self._obj_root():
            out["kv_fabric"] = self.kv_fabric_report()
        if self.sanitizer is not None:
            out["sanitizer"] = self.sanitizer.report()
        if self.incidents is not None:
            out["incidents"] = self.incidents.stats()
        if self.actuator is not None:
            out["actuation"] = {
                "ticks": self.actuator.ticks,
                "decisions": len(self.actuator.journal),
                "counts": dict(self.actuator.journal.counts),
                "scale_events": dict(self.scale_events),
                "acked": self.connector.acked() if self.connector else 0,
            }
        return out
